"""Cross-tenant continuous batching tests (pipeline/fleet._BatchFormer
+ the batch-aware admission/shed policies + the v10 telemetry fields).

The contract under test:
- grouping: only lanes sharing a plan family (the SAME SharedPlanCache
  processor) ever ride one batch; a foreign-family lane stays solo;
- linger deadline: a partial batch flushes once its oldest offer has
  waited ``fleet_batch_linger_ms`` — and a LONE tenant never waits at
  all (the idle scheduler flushes immediately);
- priority fill: when a flush holds more offers than one batch takes,
  high-priority streams ride the first dispatch;
- ragged tail: a leftover single offer goes through the lane's plain
  solo-dispatch path (never a B=1 vmap trace);
- bulkheads: a victim's demotion swaps in an unshared processor, which
  drops it out of the batch group — neighbors keep batching on the
  shared program;
- equality: batched fleet outputs match solo goldens — decisions
  exact, float time series within the documented vmap tolerance;
- no busy-wait: the event-driven scheduler wakeup keeps
  ``fleet_idle_waits`` bounded while a slow sink stalls the fleet.
"""

import json
import os
import time

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.pipeline.fleet import (StreamFleet, StreamSpec,
                                     _BatchFormer)
from srtb_tpu.pipeline.runtime import Pipeline
from srtb_tpu.resilience.admission import AdmissionController
from srtb_tpu.resilience.degrade import FleetShedPolicy
from srtb_tpu.utils import telemetry
from srtb_tpu.utils.metrics import metrics

N = 1 << 13
SEGMENTS = 4


@pytest.fixture(autouse=True)
def _fresh_metrics():
    metrics.reset()
    yield
    metrics.reset()


def _mkcfg(tmp, tag, infile, **kw):
    base = dict(
        baseband_input_count=N, baseband_input_bits=8,
        baseband_freq_low=1405.0, baseband_bandwidth=64.0,
        baseband_sample_rate=128e6, dm=0.05,
        input_file_path=infile,
        baseband_output_file_prefix=os.path.join(str(tmp), tag + "_"),
        spectrum_channel_count=64,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        baseband_reserve_sample=True,
        writer_thread_count=0, fft_strategy="four_step",
        inflight_segments=2, retry_backoff_base_s=0.001)
    base.update(kw)
    return Config(**base)


def _make_bb(tmp, tag, seed):
    path = os.path.join(str(tmp), f"bb_{tag}.bin")
    make_dispersed_baseband(
        N * SEGMENTS, 1405.0, 64.0, 0.05,
        pulse_positions=[N // 2 + j * N for j in range(SEGMENTS)],
        pulse_amp=30.0, nbits=8, seed=seed).tofile(path)
    return path


class _Cap:
    """Decision-capturing sink."""

    def __init__(self):
        self.out = []

    def push(self, work, positive):
        det = work.detect
        self.out.append((np.asarray(det.signal_counts).copy(),
                         np.asarray(det.zero_count).copy(),
                         np.asarray(det.time_series).copy(),
                         bool(positive)))


def _solo(cfg):
    cap = _Cap()
    with Pipeline(cfg, sinks=[cap]) as pipe:
        stats = pipe.run()
    return stats, cap.out


def _decisions_match(a, b, ts_exact=True):
    """Decisions exact; time series bitwise or vmap-allclose."""
    assert len(a) == len(b)
    for i, (x, y) in enumerate(zip(a, b)):
        assert np.array_equal(x[0], y[0]), f"signal_counts @ {i}"
        assert np.array_equal(x[1], y[1]), f"zero_count @ {i}"
        if ts_exact:
            assert np.array_equal(x[2], y[2]), f"time_series @ {i}"
        else:
            # the documented vmap tolerance (archive micro-batch
            # precedent): amplitude-relative atol for float32
            # reassociation in the batched plan
            np.testing.assert_allclose(
                x[2], y[2], rtol=1e-5,
                atol=1e-4 * max(float(np.abs(y[2]).max()), 1.0),
                err_msg=f"time_series beyond vmap tolerance @ {i}")
        assert x[3] == y[3], f"positive @ {i}"


def _journal(path):
    return [json.loads(line) for line in open(path)
            if line.strip().startswith("{")]


# ------------------------------------------------ end-to-end equality


def test_batched_fleet_matches_solo_within_vmap_tolerance(tmp_path):
    """3 same-family streams, fleet_batch_max=2: batched AND ragged-
    tail solo dispatches both occur; every stream's decisions match
    its solo golden (float series within the vmap tolerance), the
    plan compiles once, and the journal accounts every batch."""
    tags = ("s0", "s1", "s2")
    bbs = {t: _make_bb(tmp_path, t, i) for i, t in enumerate(tags)}
    solo = {}
    for t, bb in bbs.items():
        metrics.reset()
        solo[t] = _solo(_mkcfg(tmp_path, t + "solo", bb))
    metrics.reset()
    caps = {t: _Cap() for t in bbs}
    jp = {t: os.path.join(str(tmp_path), f"j_{t}.jsonl") for t in bbs}
    fleet = StreamFleet([
        StreamSpec(name=t,
                   cfg=_mkcfg(tmp_path, t, bb, fleet_batch_max=2,
                              telemetry_journal_path=jp[t]),
                   sinks=[caps[t]])
        for t, bb in bbs.items()])
    res = fleet.run()
    fleet.close()
    assert all(r.status == "done" and r.dropped == 0
               for r in res.values())
    assert fleet.plans.compiles == 1 and fleet.plans.hits == 2
    assert metrics.get("batched_dispatches") >= 1
    for t in tags:
        assert res[t].drained == solo[t][0].segments
        _decisions_match(caps[t].out, solo[t][1], ts_exact=False)
    # journal accounting: batched records carry batch_size (== 2 at
    # this batch_max), solo/ragged-tail records omit it entirely
    sizes = []
    for t in tags:
        for r in _journal(jp[t]):
            assert r["v"] == 11 and r["stream"] == t
            if "batch_size" in r:
                sizes.append(r["batch_size"])
                assert r["batch_size"] == 2
                assert r["batch_wait_ms"] >= 0.0
    assert len(sizes) == int(metrics.get("batched_segments"))
    assert len(sizes) == 2 * int(metrics.get("batched_dispatches"))


def test_grouping_by_plan_cache_key(tmp_path):
    """Two same-shape streams + one foreign-family stream (different
    channel count = different plan_cache_key): only the family pair
    ever batches; the loner drains through solo dispatches."""
    bbs = {t: _make_bb(tmp_path, t, i)
           for i, t in enumerate(("a0", "a1", "lone"))}
    metrics.reset()
    caps = {t: _Cap() for t in bbs}
    jp = {t: os.path.join(str(tmp_path), f"j_{t}.jsonl") for t in bbs}

    def cfg_for(t, bb):
        extra = {"spectrum_channel_count": 32} if t == "lone" else {}
        return _mkcfg(tmp_path, t, bb, fleet_batch_max=4,
                      telemetry_journal_path=jp[t], **extra)

    fleet = StreamFleet([
        StreamSpec(name=t, cfg=cfg_for(t, bb), sinks=[caps[t]])
        for t, bb in bbs.items()])
    res = fleet.run()
    fleet.close()
    assert all(r.status == "done" for r in res.values())
    assert metrics.get("batched_dispatches") >= 1
    # the loner's journal never carries batch_size; the family's does
    assert all("batch_size" not in r for r in _journal(jp["lone"]))
    by_stream = metrics.by_label("batched_segments")
    assert "lone" not in by_stream
    assert set(by_stream) <= {"a0", "a1"} and by_stream


def test_lone_tenant_never_waits_out_the_linger(tmp_path):
    """One stream, an hour-long linger, batch never fillable: the
    idle scheduler flushes the partial batch immediately — the run
    completes in seconds, unbatched."""
    bb = _make_bb(tmp_path, "solo1", 0)
    cap = _Cap()
    t0 = time.perf_counter()
    fleet = StreamFleet([StreamSpec(
        name="solo1",
        cfg=_mkcfg(tmp_path, "solo1", bb, fleet_batch_max=4,
                   fleet_batch_linger_ms=3_600_000.0),
        sinks=[cap])])
    res = fleet.run()
    fleet.close()
    elapsed = time.perf_counter() - t0
    assert res["solo1"].status == "done"
    assert res["solo1"].drained == len(cap.out) > 0
    assert elapsed < 60.0, "lone tenant waited on the linger deadline"
    assert metrics.get("batched_dispatches") == 0


# --------------------------------------------- former unit semantics


class _StubLane:
    """Just enough lane surface for _BatchFormer formation policy."""

    def __init__(self, name, priority, proc):
        self.name = name
        self.priority = priority
        self.pipe = type("P", (), {"processor": proc})()


def _former(batch_max, linger_s=0.0):
    f = _BatchFormer.__new__(_BatchFormer)
    _BatchFormer.__init__(f, fleet=None, batch_max=batch_max,
                          linger_s=linger_s)
    return f


def test_former_priority_fill_and_ragged_tail():
    """Flush order: priority desc, offer age asc; a leftover single
    offer routes to the solo-dispatch fallback, never a B=1 batch."""
    proc = object()
    former = _former(batch_max=4)
    shared_calls, solo_calls = [], []
    former._dispatch_shared = \
        lambda p, slots: shared_calls.append((p, list(slots)))
    former._single_fallback = \
        lambda slot, requeue=False: solo_calls.append(slot)
    lanes = [_StubLane("low", 0, proc), _StubLane("high", 9, proc),
             _StubLane("mid", 1, proc)]
    for i, lane in enumerate(lanes):
        former.offer(lane, (object(), 0.0, 0), i)
    assert not shared_calls  # 3 offers < batch_max: still forming
    assert former.flush_all()
    [(got_proc, slots)] = shared_calls
    assert got_proc is proc
    assert [s.lane.name for s in slots] == ["high", "mid", "low"]
    assert not solo_calls

    # 5th offer after an auto-flush at batch_max leaves a tail of one
    shared_calls.clear()
    for i, lane in enumerate(lanes + lanes[:2]):
        former.offer(lane, (object(), 0.0, 10 + i), 10 + i)
    assert len(shared_calls) == 1 and len(shared_calls[0][1]) == 4
    assert former.flush_all()
    assert len(solo_calls) == 1  # the ragged tail went solo


def test_former_linger_deadline_pump():
    """pump() flushes a partial family only once its oldest live
    offer has waited past the linger deadline."""
    former = _former(batch_max=4, linger_s=0.02)
    solo_calls = []
    former._single_fallback = \
        lambda slot, requeue=False: solo_calls.append(slot)
    former.offer(_StubLane("a", 0, object()), (object(), 0.0, 0), 0)
    assert not former.pump()          # deadline not reached
    assert not solo_calls
    time.sleep(0.03)
    assert former.pump()              # oldest offer now past linger
    assert len(solo_calls) == 1
    assert not former.pump()          # nothing left


def test_former_groups_by_processor_identity():
    """Offers from different processors never share a group (the
    plan_cache_key contract: one shared processor per family)."""
    pa, pb = object(), object()
    former = _former(batch_max=2)
    shared_calls = []
    former._dispatch_shared = \
        lambda p, slots: shared_calls.append((p, list(slots)))
    former._single_fallback = lambda slot, requeue=False: None
    former.offer(_StubLane("a0", 0, pa), (object(), 0.0, 0), 0)
    former.offer(_StubLane("b0", 0, pb), (object(), 0.0, 0), 0)
    assert not shared_calls  # one offer per family: nothing fillable
    former.offer(_StubLane("a1", 0, pa), (object(), 0.0, 0), 0)
    assert len(shared_calls) == 1  # family A filled at 2
    assert shared_calls[0][0] is pa
    assert {s.lane.name for s in shared_calls[0][1]} == {"a0", "a1"}


# ------------------------------------------------ bulkhead: demotion


def test_victim_demotion_exits_batch_group(tmp_path):
    """A victim OOM demotes the victim's plan (an UNSHARED processor
    swap): its later segments leave the batch group, neighbors keep
    batching, decisions stay exact, attribution stays per-stream."""
    tags = ("v", "h0", "h1")
    bbs = {t: _make_bb(tmp_path, t, i) for i, t in enumerate(tags)}
    solo = {}
    for t, bb in bbs.items():
        metrics.reset()
        solo[t] = _solo(_mkcfg(tmp_path, t + "solo", bb))
    plan = "v:dispatch:oom@1"
    metrics.reset()
    caps = {t: _Cap() for t in bbs}
    jp = {t: os.path.join(str(tmp_path), f"j_{t}.jsonl") for t in bbs}
    fleet = StreamFleet([
        StreamSpec(name=t,
                   cfg=_mkcfg(tmp_path, t, bb, fleet_batch_max=3,
                              fault_plan=plan,
                              telemetry_journal_path=jp[t]),
                   sinks=[caps[t]])
        for t, bb in bbs.items()])
    res = fleet.run()
    fleet.close()
    assert all(r.status == "done" for r in res.values())
    assert metrics.by_label("plan_demotions") == {"v": 1.0}
    assert res["v"].extras["plan"] != res["h0"].extras["plan"]
    for t in ("h0", "h1"):
        _decisions_match(caps[t].out, solo[t][1], ts_exact=False)
    _decisions_match(caps["v"].out, solo["v"][1], ts_exact=False)
    # the victim's demoted (unshared) processor never batches again:
    # no victim journal record AT or AFTER the fault index carries
    # batch_size
    for r in _journal(jp["v"]):
        if r["segment"] >= 1:
            assert "batch_size" not in r, \
                "demoted victim still riding the shared batch"
    # neighbors kept batching on the shared program
    by_stream = metrics.by_label("batched_segments")
    assert set(by_stream) <= {"h0", "h1", "v"}
    assert "h0" in by_stream or "h1" in by_stream


# -------------------------------------------- scheduler: no busy-wait


def test_event_driven_scheduler_no_busy_wait(tmp_path):
    """A slow sink parks the fleet repeatedly; the condition-variable
    wakeup must wait in O(50 ms) slices, not spin at the old 2 ms
    poll — fleet_idle_waits stays two orders of magnitude below what
    a busy-wait over the same wall time would log."""
    bb = _make_bb(tmp_path, "slow", 0)

    class _SlowCap(_Cap):
        def push(self, work, positive):
            time.sleep(0.25)
            super().push(work, positive)

    cap = _SlowCap()
    t0 = time.perf_counter()
    fleet = StreamFleet([StreamSpec(
        name="slow", cfg=_mkcfg(tmp_path, "slow", bb), sinks=[cap])])
    res = fleet.run()
    fleet.close()
    elapsed = time.perf_counter() - t0
    assert res["slow"].status == "done" and len(cap.out) > 0
    waits = int(metrics.get("fleet_idle_waits"))
    # busy-wait at the old 2 ms sleep over the same stalled wall time
    # would log ~elapsed/0.002 waits; the cond-var waits in >= 50 ms
    # slices (plus real wakeups), so give 4x headroom over elapsed/0.05
    assert waits <= max(40, int(elapsed / 0.05 * 4)), \
        f"{waits} idle waits in {elapsed:.2f}s looks like a busy-wait"


# ------------------------------- batch-aware admission + shed policy


def test_admission_eviction_prefers_loner_family():
    """An outranking request evicts, within the lowest-priority band,
    the newest stream whose plan family has NO co-tenant — kicking a
    batch-group member would cost its whole family the batch density."""
    ac = AdmissionController(max_streams=1, queue_limit=2)
    assert ac.request("run0", priority=0, plan_key="k1") == "admit"
    # queue fills: the LONER (k2) arrives FIRST, the co-tenant (k1)
    # second — pre-batching eviction would take the newest (k1)
    assert ac.request("lone", priority=0, plan_key="k2") == "queue"
    assert ac.request("mate", priority=0, plan_key="k1") == "queue"
    assert ac.request("vip", priority=5, plan_key=None) == "queue"
    assert ac.rejected == ["lone"]
    assert ac.queued == ["vip", "mate"]


def test_admission_eviction_unchanged_without_plan_keys():
    """All-None plan keys reproduce the pre-batching behavior exactly:
    the newest arrival of the lowest band is evicted."""
    ac = AdmissionController(max_streams=1, queue_limit=2)
    assert ac.request("run0", priority=0) == "admit"
    assert ac.request("q0", priority=0) == "queue"
    assert ac.request("q1", priority=0) == "queue"
    assert ac.request("vip", priority=5) == "queue"
    assert ac.rejected == ["q1"]


def test_shed_prefers_unbatched_within_band():
    """Fleet shedding under pressure takes the UNBATCHED lane first
    within a priority band (shedding a batch member degrades its
    whole family); restore order mirrors it."""
    pol = FleetShedPolicy(hold=1)
    lanes = [("bat", 0, True, True), ("solo", 0, True, False)]
    assert pol.observe(1.0, False, lanes) == {"solo"}
    assert pol.observe(1.0, False, lanes) == {"solo", "bat"}
    # relief: the batched member comes back first
    assert pol.observe(0.0, False, lanes) == {"solo"}
    # 3-tuple callers (no batching) still work
    pol2 = FleetShedPolicy(hold=1)
    assert pol2.observe(1.0, False,
                        [("a", 0, True), ("b", 1, True)]) == {"a"}


# ------------------------------------------------- telemetry schema


def test_span_v10_batch_fields_omitted_when_solo():
    assert telemetry.SPAN_SCHEMA_VERSION == 11
    rec = telemetry.segment_span(0, {"dispatch": 0.1}, 0, 0, False,
                                 1024)
    assert "batch_size" not in rec and "batch_wait_ms" not in rec
    rec = telemetry.segment_span(0, {"dispatch": 0.1}, 0, 0, False,
                                 1024, batch_size=3,
                                 batch_wait_ms=1.234)
    assert rec["batch_size"] == 3
    assert rec["batch_wait_ms"] == 1.234


# ------------------------------------------- archive cross-file leg


def test_archive_replay_fleet_batch(tmp_path):
    """Many small files, micro_batch=1, fleet_batch armed: the replay
    report shows cross-file batched dispatches and no failures."""
    from srtb_tpu.pipeline.archive import ArchiveReplay

    files = [_make_bb(tmp_path, f"f{i}", i) for i in range(3)]
    base = _mkcfg(tmp_path, "arch", files[0])
    rep = ArchiveReplay(base, files, str(tmp_path / "arch_out"),
                        lanes=3, micro_batch=1, inflight=2,
                        fleet_batch=3, manifest=False).run()
    assert rep.failed == 0
    assert rep.batched_dispatches >= 1
    assert rep.batched_segments >= 2 * rep.batched_dispatches
