"""Fleet control tower (srtb_tpu/obs/): digests, store, aggregator,
cross-device trace join, regression watch, status + console, /fleet."""

import gzip
import json
import math
import os

import numpy as np
import pytest

from srtb_tpu.obs.digest import QuantileDigest
from srtb_tpu.obs.rollup import Aggregator
from srtb_tpu.obs.store import RollupStore


def _span(ts, seg, stream="s0", device="dev0", plan="p1", **extra):
    rec = {"type": "segment_span", "ts": float(ts), "segment": int(seg),
           "stream": stream, "device": device, "active_plan": plan,
           "samples": 4096,
           "stages_ms": {"ingest": 1.0, "dispatch": 2.0, "sink": 0.5}}
    rec.update(extra)
    return rec


def _write_journal(path, records):
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


# ------------------------------------------------------------ digest


def test_digest_percentiles_within_documented_error():
    """Any quantile estimate is within ``alpha`` relative error of the
    exact sample at that rank (one order statistic of slack covers the
    interpolation-convention difference vs numpy)."""
    rng = np.random.default_rng(42)
    vals = rng.lognormal(mean=0.0, sigma=1.0, size=20000)
    d = QuantileDigest(alpha=0.01)
    for v in vals:
        d.add(float(v))
    s = np.sort(vals)
    for q in (0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99):
        est = d.quantile(q)
        rank = max(1, math.ceil(q * len(s)))
        neighborhood = s[max(0, rank - 2):rank + 1]
        rel = min(abs(est - x) / x for x in neighborhood)
        assert rel <= d.alpha + 1e-9, (q, est, rel)
    assert d.quantile(0.0) == float(s[0])
    assert d.quantile(1.0) == float(s[-1])


def test_digest_merge_equals_whole():
    """Digesting a stream in three parts then merging equals digesting
    it whole — exactly (same buckets, same counts)."""
    rng = np.random.default_rng(7)
    vals = rng.exponential(scale=3.0, size=3000)
    whole = QuantileDigest()
    parts = [QuantileDigest() for _ in range(3)]
    for i, v in enumerate(vals):
        whole.add(float(v))
        parts[i % 3].add(float(v))
    merged = parts[0]
    merged.merge(parts[1])
    merged.merge(parts[2])
    assert merged.buckets == whole.buckets
    assert merged.count == whole.count
    assert merged.min == whole.min and merged.max == whole.max
    for q in (0.5, 0.95, 0.99):
        assert merged.quantile(q) == whole.quantile(q)


def test_digest_round_trip_and_guards():
    d = QuantileDigest()
    for v in (0.0, 1e-12, 0.5, 100.0):
        d.add(v)
    back = QuantileDigest.from_dict(
        json.loads(json.dumps(d.to_dict(), sort_keys=True)))
    assert back.buckets == d.buckets and back.zeros == d.zeros == 2
    assert back.quantile(0.99) == d.quantile(0.99)
    with pytest.raises(ValueError):
        d.add(-1.0)
    with pytest.raises(ValueError):
        d.add(float("nan"))
    with pytest.raises(ValueError):
        QuantileDigest(alpha=0.01).merge(QuantileDigest(alpha=0.02))
    assert math.isnan(QuantileDigest().quantile(0.5))


# ------------------------------------------------------------- store


def test_store_last_wins_and_compaction_idempotent(tmp_path):
    store = RollupStore(str(tmp_path / "store"))
    store.append_many([
        {"k": "m:1:a", "minute": 1, "segments": 2},
        {"k": "m:1:a", "minute": 1, "segments": 5},  # supersedes
        {"k": "m:2:a", "minute": 2, "segments": 1},
        {"k": "d:stage:x", "digest": {"count": 3}},  # minute-less
    ])
    assert store.latest()["m:1:a"]["segments"] == 5
    r1 = store.compact()
    assert r1["rows"] == 3

    def seg_bytes():
        return {n: (tmp_path / "store" / "segments" / n).read_bytes()
                for n in os.listdir(tmp_path / "store" / "segments")}

    b1 = seg_bytes()
    r2 = store.compact()
    assert r2["rows"] == 3 and seg_bytes() == b1  # byte-identical
    # active arm truncated; state survives in segments
    assert store.latest()["m:1:a"]["segments"] == 5
    # a re-appended duplicate collapses again, not double-counts
    store.append({"k": "m:2:a", "minute": 2, "segments": 1})
    store.compact()
    assert seg_bytes() == b1
    with pytest.raises(ValueError):
        store.append({"minute": 3})  # unkeyed row = programming error


def test_store_retention_drops_old_minutes(tmp_path):
    store = RollupStore(str(tmp_path / "s"), retention_minutes=10)
    store.append_many(
        [{"k": f"m:{m}", "minute": m} for m in (0, 5, 90, 100)]
        + [{"k": "d:meta"}])  # minute-less rows never expire
    rep = store.compact()
    assert rep["dropped"] == 2  # minutes 0 and 5 are > 10 behind 100
    keys = set(store.latest())
    assert keys == {"m:90", "m:100", "d:meta"}


# -------------------------------------------------------- aggregator


def test_aggregator_rollup_counters_and_digests(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    recs = [_span(60.0 + i, i, segments_dropped=(1 if i >= 3 else 0),
                  detections=1, device_ms=2.0, batch_size=2)
            for i in range(5)]
    _write_journal(jp, recs)
    store = RollupStore(str(tmp_path / "store"))
    agg = Aggregator(store, journals=[jp])
    assert agg.poll()["spans"] == 5
    agg.flush()
    state = store.latest()
    row = state["m:1:s0:dev0:p1"]  # ts 60-64 -> minute 1
    assert row["segments"] == 5 and row["detections"] == 5
    # cumulative 0,0,0,1,1 -> one localized loss delta
    assert row["loss_delta"] == 1
    assert row["device_ms_sum"] == pytest.approx(10.0)
    assert row["batch_segments"] == 10
    dig = QuantileDigest.from_dict(
        state["d:stage:dispatch"]["digest"])
    assert dig.count == 5
    # per-plan samples feed the regression watch: stage sums in s
    assert agg.plans() == ["p1"]
    assert agg.segment_seconds("p1") == pytest.approx([3.5e-3] * 5)


def test_aggregator_resumes_active_journal_by_offset(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    _write_journal(jp, [_span(60.0 + i, i) for i in range(4)])
    store_dir = str(tmp_path / "store")
    agg = Aggregator(RollupStore(store_dir), journals=[jp])
    assert agg.poll()["spans"] == 4
    agg.flush()
    # torn tail (no newline) is left for the next poll
    with open(jp, "a") as f:
        f.write(json.dumps(_span(64.0, 4)) + "\n")
        f.write('{"type": "segment_span", "ts": 65')
    agg2 = Aggregator(RollupStore(store_dir), journals=[jp])
    assert agg2.poll()["spans"] == 1  # only the complete new record
    agg2.flush()
    with open(jp, "a") as f:
        f.write('.0, "segment": 5, "stream": "s0", '
                '"stages_ms": {"ingest": 1.0}}\n')
    agg3 = Aggregator(RollupStore(store_dir), journals=[jp])
    assert agg3.poll()["spans"] == 1  # the completed torn record
    assert agg3.poll()["spans"] == 0  # and nothing twice


def test_aggregator_resumes_from_torn_gz_without_double_count(
        tmp_path):
    """A rotated .gz generation read torn, then complete: only the
    records beyond the first read are ingested (total == exact)."""
    jp = str(tmp_path / "j.jsonl")
    recs = [_span(60.0 + i, i) for i in range(20)]
    payload = "".join(json.dumps(r) + "\n" for r in recs).encode()
    whole = gzip.compress(payload)
    gen = jp + ".1.gz"
    with open(gen, "wb") as f:
        f.write(whole[:len(whole) * 2 // 3])  # torn tail
    _write_journal(jp, [_span(100.0, 20)])  # active arm: 1 span
    store_dir = str(tmp_path / "store")
    agg = Aggregator(RollupStore(store_dir), journals=[jp])
    first = agg.poll()["spans"]
    assert 1 <= first < 21  # readable gz prefix + the active span
    agg.flush()
    with open(gen, "wb") as f:
        f.write(whole)  # rotation completed / repaired
    agg2 = Aggregator(RollupStore(store_dir), journals=[jp])
    second = agg2.poll()["spans"]
    assert first + second == 21  # no span counted twice, none lost
    agg2.flush()
    assert Aggregator(RollupStore(store_dir),
                      journals=[jp]).poll()["spans"] == 0


def test_aggregator_detects_rotation_of_active_arm(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    _write_journal(jp, [_span(60.0 + i, i) for i in range(3)])
    store_dir = str(tmp_path / "store")
    agg = Aggregator(RollupStore(store_dir), journals=[jp])
    assert agg.poll()["spans"] == 3
    agg.flush()
    # rotate: old contents become the .1.gz generation, fresh active
    with open(jp, "rb") as f:
        old = f.read()
    with open(jp + ".1.gz", "wb") as f:
        f.write(gzip.compress(old))
    _write_journal(jp, [_span(120.0 + i, 3 + i) for i in range(2)])
    agg2 = Aggregator(RollupStore(store_dir), journals=[jp])
    # generation re-read is cursor-skipped; fresh active reads from 0
    assert agg2.poll()["spans"] == 2


def test_aggregator_event_dump_dedup(tmp_path):
    ev = str(tmp_path / "events.jsonl")
    rows = [{"t": 1.5, "ts": 61.5, "type": "fleet.migrate",
             "stream": "s0", "seg": 3, "info": "dev0->dev1",
             "thread": "ctl"},
            {"t": 2.5, "ts": 62.5, "type": "stage.sink", "stream": "s0",
             "seg": 3, "thread": "sink"}]  # not a fleet event
    with open(ev, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    store = RollupStore(str(tmp_path / "store"))
    agg = Aggregator(store, journals=[], events_dumps=[ev])
    assert agg.poll()["events"] == 1
    # dumps are full rewrites: re-reading must not re-count
    assert agg.poll()["events"] == 0
    agg.flush()
    evs = [r for r in store.latest().values()
           if r["type"] == "fleet_event"]
    assert len(evs) == 1 and evs[0]["kind"] == "fleet.migrate"
    assert evs[0]["info"] == "dev0->dev1"


# -------------------------------------------------- cross-device join


def test_trace_join_crosses_device_tracks(tmp_path):
    from srtb_tpu.obs import trace_join
    from srtb_tpu.tools.trace_export import validate
    jp = str(tmp_path / "j.jsonl")
    _write_journal(jp, [
        _span(60.0 + i, i, device=("dev0" if i < 3 else "dev1"))
        for i in range(6)])
    ev = str(tmp_path / "events.jsonl")
    with open(ev, "w") as f:
        for i in range(6):
            f.write(json.dumps(
                {"t": 10.0 + i, "ts": 60.0 + i, "type": "stage.dispatch",
                 "trace": i + 1, "stream": "s0", "seg": i,
                 "dur_ms": 2.0, "thread": "eng"}) + "\n")
            f.write(json.dumps(
                {"t": 10.4 + i, "ts": 60.4 + i, "type": "stage.sink",
                 "trace": i + 1, "stream": "s0", "seg": i,
                 "dur_ms": 0.5, "thread": "sink"}) + "\n")
        f.write(json.dumps(
            {"t": 12.5, "ts": 62.5, "type": "fleet.migrate", "trace": 0,
             "stream": "s0", "seg": -1, "info": "dev0->dev1",
             "thread": "ctl"}) + "\n")
    doc = trace_join.join([ev], [jp])
    assert validate(doc) == []  # the same structural gate as CI
    assert doc["otherData"]["devices"] == ["dev0", "dev1"]
    assert doc["otherData"]["stream_devices"]["s0"] == ["dev0", "dev1"]
    # the migration visual: the lane flow chain spans BOTH device pids
    lane = [e for e in doc["traceEvents"] if e.get("cat") == "flow"
            and e["id"] >= trace_join.LANE_FLOW_BASE]
    assert lane and len({e["pid"] for e in lane}) == 2
    # unmapped events would fall to a host track; here all map
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert names == {"device:dev0", "device:dev1"}


def test_trace_join_cli(tmp_path, capsys):
    from srtb_tpu.obs import trace_join
    jp = str(tmp_path / "j.jsonl")
    _write_journal(jp, [_span(60.0, 0)])
    ev = str(tmp_path / "e.jsonl")
    with open(ev, "w") as f:
        f.write(json.dumps({"t": 1.0, "ts": 60.0,
                            "type": "stage.dispatch", "trace": 1,
                            "stream": "s0", "seg": 0, "dur_ms": 1.0,
                            "thread": "eng"}) + "\n")
    out = str(tmp_path / "trace.json")
    assert trace_join.main([ev, "--journals", jp, "--out", out]) == 0
    doc = json.load(open(out))
    assert doc["traceEvents"]


# --------------------------------------------------- regression watch


def test_regression_watch_trips_once_and_latches(tmp_path):
    from srtb_tpu.obs.regression import RegressionWatch
    from srtb_tpu.utils import perf_ledger as PL
    ledger = str(tmp_path / "ledger.jsonl")
    rng = np.random.default_rng(0)
    base = (0.010 + rng.normal(0, 2e-4, 24)).tolist()
    PL.PerfLedger(ledger).append(PL.make_record(
        "test", 0.01, "s/segment", plan="p1", samples_s=base,
        host_fp="", git_sha_value=""))
    inc = str(tmp_path / "incidents")
    watch = RegressionWatch(ledger, incident_dir=inc, host_fp="")
    slow = (0.020 + rng.normal(0, 2e-4, 24)).tolist()
    v = watch.check("p1", slow)
    assert v["checked"] and v["regression"] and v["escalated"]
    bundles = [n for n in os.listdir(inc)
               if os.path.isdir(os.path.join(inc, n))]
    assert len(bundles) == 1  # exactly one incident bundle
    # the latch: a sustained regression is ONE incident, not one/tick
    v2 = watch.check("p1", slow)
    assert v2["regression"] and v2["escalated"] is False
    assert len([n for n in os.listdir(inc)
                if os.path.isdir(os.path.join(inc, n))]) == 1
    # clean samples against the same baseline: no trip
    clean = (0.010 + rng.normal(0, 2e-4, 24)).tolist()
    watch2 = RegressionWatch(ledger,
                             incident_dir=str(tmp_path / "inc2"),
                             host_fp="")
    vc = watch2.check("p1", clean)
    assert vc["checked"] and not vc["regression"]
    assert not os.path.isdir(str(tmp_path / "inc2")) or not os.listdir(
        str(tmp_path / "inc2"))


def test_regression_watch_needs_enough_samples(tmp_path):
    from srtb_tpu.obs.regression import RegressionWatch
    watch = RegressionWatch(str(tmp_path / "none.jsonl"), host_fp="")
    v = watch.check("p1", [0.01] * 3)
    assert v["checked"] is False and "3 live samples" in v["reason"]
    v = watch.check("p1", [0.01] * 24)
    assert v["checked"] is False and "ledger" in v["reason"]


def test_perf_ledger_history_filters(tmp_path):
    from srtb_tpu.utils import perf_ledger as PL
    recs = [
        PL.make_record("t", 1.0, "u", plan="p1", samples_s=[1.0, 2.0],
                       host_fp="hostA", git_sha_value=""),
        PL.make_record("t", 1.0, "u", plan="p1", samples_s=[3.0],
                       host_fp="hostB", git_sha_value=""),
        PL.make_record("t", 1.0, "u", plan="p2", samples_s=[9.0],
                       host_fp="hostA", git_sha_value=""),
        PL.make_record("t", 1.0, "u", plan="p1",
                       host_fp="hostA", git_sha_value=""),  # no samples
    ]
    assert PL.history(recs, "p1", host_fp="hostA") == [1.0, 2.0]
    assert PL.history(recs, "p1") == [1.0, 2.0, 3.0]
    assert PL.history(recs, "p2", host_fp="hostB") == []
    many = [PL.make_record("t", 1.0, "u", plan="p1",
                           samples_s=[float(i)], host_fp="",
                           git_sha_value="") for i in range(6)]
    assert PL.history(many, "p1", max_records=3) == [3.0, 4.0, 5.0]


# --------------------------------------- status, console, /fleet


def test_fleet_status_and_console_render(tmp_path):
    from srtb_tpu.obs.status import fleet_status
    from srtb_tpu.tools import console
    from srtb_tpu.utils.metrics import metrics
    metrics.reset()
    try:
        metrics.set("fleet_device_state", 0, labels={"device": "dev0"})
        metrics.set("fleet_device_state", 2, labels={"device": "dev1"})
        metrics.set("fleet_device_lanes", 3, labels={"device": "dev0"})
        metrics.add("migrations", 2)
        metrics.add("migrations", labels={"device": "dev0"}, value=2)
        metrics.add("device_drains", labels={"device": "dev1"})
        metrics.set("roofline_frac", 0.062)
        metrics.add("batched_dispatches", 4)
        metrics.add("batched_segments", 10)
        # a store with a migration timeline row
        store = RollupStore(str(tmp_path / "store"))
        store.append({"k": "e:1", "type": "fleet_event", "minute": 1,
                      "ts": 61.0, "kind": "fleet.migrate",
                      "stream": "s0", "seg": 3, "info": "dev0->dev1"})
        status = fleet_status(store_dir=str(tmp_path / "store"))
        assert status["devices"]["dev0"]["state"] == "ok"
        assert status["devices"]["dev1"]["state"] == "halted"
        assert status["devices"]["dev0"]["lanes"] == 3
        assert status["pool"]["migrations"] == 2
        assert status["batch"]["occupancy"] == 2.5
        assert status["store"]["timeline"][0]["kind"] == "fleet.migrate"
        text = console.render(status)
        assert "POOL" in text and "dev1" in text and "halted" in text
        assert "fleet.migrate" in text and "dev0->dev1" in text
        assert "occupancy=2.50" in text
    finally:
        metrics.reset()


def test_fleet_endpoint_and_pool_aggregated_metrics(tmp_path):
    import urllib.request
    from srtb_tpu.gui.server import WaterfallHTTPServer
    from srtb_tpu.utils.metrics import metrics
    metrics.reset()
    try:
        metrics.set("fleet_device_state", 0, labels={"device": "dev0"})
        metrics.set("fleet_device_state", 1, labels={"device": "dev1"})
        metrics.add("migrations", labels={"device": "dev1"})
        srv = WaterfallHTTPServer(
            str(tmp_path), port=0,
            fleet_store_dir=str(tmp_path / "store")).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            with urllib.request.urlopen(base + "/fleet",
                                        timeout=10) as r:
                doc = json.loads(r.read().decode())
            assert set(doc["devices"]) == {"dev0", "dev1"}
            assert doc["devices"]["dev1"]["state"] == "draining"
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as r:
                prom = r.read().decode()
            # pool aggregates render as ordinary flat families with
            # their own contiguous HELP/TYPE (strict-expfmt safe)
            assert "srtb_fleet_device_state_pool_max 1" in prom
            assert "srtb_fleet_device_state_pool_sum 1" in prom
            assert "srtb_migrations_pool_sum 1" in prom
            assert ("# HELP srtb_migrations_pool_sum Sum of "
                    "migrations across pool members") in prom
            # snapshot/prometheus parity holds for the new families
            snap = metrics.snapshot()
            assert snap["migrations_pool_sum"] == 1.0
            assert snap["fleet_device_state_pool_max"] == 1.0
            # labeled twins still render (per-device series intact)
            assert 'srtb_migrations{device="dev1"} 1' in prom
        finally:
            srv.stop()
    finally:
        metrics.reset()


def test_console_url_mode_against_server(tmp_path, capsys):
    from srtb_tpu.gui.server import WaterfallHTTPServer
    from srtb_tpu.tools import console
    from srtb_tpu.utils.metrics import metrics
    metrics.reset()
    try:
        metrics.set("fleet_device_state", 0, labels={"device": "dev0"})
        srv = WaterfallHTTPServer(str(tmp_path), port=0).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            assert console.main(["--url", base, "--once"]) == 0
            out = capsys.readouterr().out
            assert "POOL" in out and "dev0" in out
            assert console.main(["--url", base, "--once",
                                 "--json"]) == 0
            doc = json.loads(capsys.readouterr().out)
            assert doc["devices"]["dev0"]["state"] == "ok"
        finally:
            srv.stop()
    finally:
        metrics.reset()


# -------------------------------------- telemetry_report fleet devices


def test_telemetry_report_fleet_device_section(tmp_path, capsys):
    from srtb_tpu.tools import telemetry_report as TR
    jp = str(tmp_path / "j.jsonl")
    recs = [
        # v1-era record: no stream/device — must be tolerated, skipped
        {"type": "segment_span", "ts": 59.0, "segment": 0,
         "stages_ms": {"ingest": 1.0}},
        _span(60.0, 0, stream="a", device="dev0", detections=1,
              segments_dropped=0),
        _span(61.0, 1, stream="a", device="dev0", segments_dropped=2),
        _span(62.0, 0, stream="b", device="dev1", detections=3,
              segments_dropped=0),
        # stream a migrates: the delta after the switch bills dev1
        _span(63.0, 2, stream="a", device="dev1", segments_dropped=3),
    ]
    _write_journal(jp, recs)
    fd = TR.fleet_device_stats(TR.load(jp))
    assert set(fd) == {"dev0", "dev1"}
    assert fd["dev0"] == {"spans": 2, "streams": 1, "detections": 1,
                          "segments_dropped": 2, "migrations_in": 0}
    assert fd["dev1"]["spans"] == 2 and fd["dev1"]["streams"] == 2
    assert fd["dev1"]["migrations_in"] == 1
    assert fd["dev1"]["segments_dropped"] == 1  # 3-2, post-migration
    # all-old journal: section simply absent
    assert TR.fleet_device_stats([recs[0]]) == {}
    # rendered report carries the table
    assert TR.main([jp]) == 0
    out = capsys.readouterr().out
    assert "## Fleet devices (per pool member)" in out
    assert "| dev1 | 2 | 2 | 3 | 1 | 1 |" in out
