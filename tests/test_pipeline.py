"""End-to-end pipeline tests on synthetic baseband.

The reference has no automated end-to-end test (integration was manual on
the J1644-4559 file, SURVEY.md §4); here we go further: synthesize a
dispersed pulse in quantized baseband, run the full file -> unpack -> FFT
-> RFI -> dedisperse -> waterfall -> detect -> write chain, and assert the
pulse is recovered and the output files are format-compatible.
"""

import glob
import os

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.pipeline.runtime import Pipeline, has_signal
from srtb_tpu.pipeline.segment import SegmentProcessor


@pytest.fixture(scope="module")
def synthetic_cfg(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("e2e")
    n = 1 << 18
    f_min, bw, dm = 1405.0, 64.0, 60.0
    data = make_dispersed_baseband(n * 2, f_min, bw, dm,
                                   pulse_positions=n // 2, nbits=8)
    path = str(tmp / "baseband.bin")
    data.tofile(path)
    cfg = Config(
        baseband_input_count=n,
        baseband_input_bits=8,
        baseband_format_type="simple",
        baseband_freq_low=f_min,
        baseband_bandwidth=bw,
        baseband_sample_rate=128e6,
        dm=dm,
        input_file_path=path,
        baseband_output_file_prefix=str(tmp / "out_"),
        spectrum_channel_count=1 << 8,
        signal_detect_signal_noise_threshold=6.0,
        signal_detect_max_boxcar_length=64,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        baseband_reserve_sample=True,
    )
    return cfg


def test_segment_processor_shapes(synthetic_cfg):
    cfg = synthetic_cfg
    proc = SegmentProcessor(cfg)
    raw = np.fromfile(cfg.input_file_path, dtype=np.uint8,
                      count=cfg.baseband_input_count)
    wf_ri, res = proc.process(raw)
    n_spec = cfg.baseband_input_count // 2
    assert wf_ri.shape == (2, 1, cfg.spectrum_channel_count,
                           n_spec // cfg.spectrum_channel_count)
    assert np.asarray(res.signal_counts).shape[0] == 1


def test_pipeline_detects_dispersed_pulse(synthetic_cfg):
    cfg = synthetic_cfg
    pipe = Pipeline(cfg)
    stats = pipe.run()
    assert stats.segments >= 2  # overlap-save re-reads the tail
    assert stats.signals >= 1, "dispersed pulse must be detected"
    # candidate files written in reference-compatible formats
    sink = pipe.sinks[0]
    assert sink.written, "no candidates written"
    files = sink.written[0]
    assert os.path.exists(files.bin_path)
    assert files.npy_paths
    wf = np.load(files.npy_paths[0])
    assert wf.dtype == np.complex64
    assert wf.shape[0] == cfg.spectrum_channel_count
    assert files.tim_paths
    ts = np.fromfile(files.tim_paths[0], dtype="<f4")
    assert ts.size > 0


def test_pipeline_without_dedispersion_misses_pulse(synthetic_cfg, tmp_path):
    """Sanity: with dm=0 the pulse stays smeared below threshold — the
    detection in the previous test is genuinely due to coherent
    dedispersion."""
    cfg = synthetic_cfg.replace(
        dm=0.0, baseband_output_file_prefix=str(tmp_path / "nodm_"))
    pipe = Pipeline(cfg)
    stats = pipe.run()
    assert stats.signals == 0


def test_hamming_window_waterfall_matches_numpy_oracle():
    """Non-rectangle windows must be applied at unpack AND divided back out
    of the dynamic spectrum after the backward C2C (ref: fft_pipe.hpp:
    346-359) — a float64 numpy transliteration of the whole chain is the
    oracle."""
    from srtb_tpu.ops import rfi as R
    from srtb_tpu.ops import window as W
    from srtb_tpu.pipeline.segment import waterfall_to_numpy

    n, channels = 1 << 12, 1 << 5
    rng = np.random.default_rng(3)
    raw = rng.integers(0, 256, size=n, dtype=np.uint8)
    cfg = Config(
        baseband_input_count=n, baseband_input_bits=8,
        baseband_format_type="simple", baseband_freq_low=1405.0,
        baseband_bandwidth=64.0, baseband_sample_rate=128e6, dm=0.0,
        spectrum_channel_count=channels,
        signal_detect_max_boxcar_length=8,
        mitigate_rfi_average_method_threshold=1e9,
        mitigate_rfi_spectral_kurtosis_threshold=1e9,
        baseband_reserve_sample=False)
    proc = SegmentProcessor(cfg, window_name="hamming")
    wf = waterfall_to_numpy(proc.process(raw)[0])[0]

    # numpy float64 oracle (dm=0 -> unit chirp; RFI thresholds disabled)
    x = raw.astype(np.float64) * W.window_coefficients(
        "hamming", n, dtype=np.float64)
    spec = np.fft.rfft(x)[:-1] * R.normalization_coefficient(
        n // 2, channels)
    wlen = (n // 2) // channels
    expect = np.fft.ifft(spec.reshape(channels, wlen), axis=-1) * wlen
    expect = expect / W.window_coefficients("hamming", wlen,
                                            dtype=np.float64)
    np.testing.assert_allclose(wf, expect.astype(np.complex64),
                               rtol=1e-3, atol=1e-3)


def test_hann_window_zero_edges_stay_finite():
    """Hann coefficients are exactly zero at the row edges; the de-apply
    must not produce inf/nan there (guarded division — the one deliberate
    deviation from the reference's raw divide)."""
    n, channels = 1 << 12, 1 << 5
    raw = np.random.default_rng(4).integers(0, 256, size=n, dtype=np.uint8)
    cfg = Config(
        baseband_input_count=n, baseband_input_bits=8,
        baseband_format_type="simple", baseband_freq_low=1405.0,
        baseband_bandwidth=64.0, baseband_sample_rate=128e6, dm=5.0,
        spectrum_channel_count=channels,
        signal_detect_max_boxcar_length=8,
        baseband_reserve_sample=False)
    proc = SegmentProcessor(cfg, window_name="hann")
    wf_ri, res = proc.process(raw)
    assert np.isfinite(np.asarray(wf_ri)).all()
    assert np.isfinite(np.asarray(res.time_series)).all()


def test_has_signal_channel_threshold_gate():
    """When too many channels are zapped the segment must be ignored
    (ref: signal_detect_pipe.hpp:343-345)."""
    class FakeDetect:
        zero_count = np.asarray(250)
        signal_counts = np.asarray([5, 2, 0])
    cfg = Config(spectrum_channel_count=256,
                 signal_detect_channel_threshold=0.9)
    assert has_signal(cfg, FakeDetect()) is False
    FakeDetect.zero_count = np.asarray(10)
    assert has_signal(cfg, FakeDetect()) is True


def test_threaded_pipeline_matches_serial(synthetic_cfg, tmp_path):
    """ThreadedPipeline (thread-per-host-stage over bounded queues) must
    find the same signals as the serial loop."""
    from srtb_tpu.pipeline.runtime import ThreadedPipeline
    cfg = synthetic_cfg.replace(
        baseband_output_file_prefix=str(tmp_path / "thr_"))
    pipe = ThreadedPipeline(cfg)
    stats = pipe.run()
    assert stats.segments >= 2
    assert stats.signals >= 1


def test_pipeline_pallas_path_matches(synthetic_cfg, tmp_path):
    """use_pallas (fused df64 chirp multiply in a Pallas kernel) must give
    the same detections as the precomputed-chirp path."""
    cfg2 = synthetic_cfg.replace(
        use_pallas=True,
        baseband_output_file_prefix=str(tmp_path / "pl_"))
    pipe = Pipeline(cfg2)
    stats = pipe.run()
    assert stats.signals >= 1


def test_pallas_path_multi_stream_matches(tmp_path):
    """use_pallas with a 2-polarization format must match the jnp path's
    detections stream for stream."""
    n = 1 << 14
    rng = np.random.default_rng(9)
    raw = rng.integers(0, 256, size=2 * n, dtype=np.uint8)
    base = dict(
        baseband_input_count=n, baseband_input_bits=8,
        baseband_format_type="naocpsr_snap1", baseband_freq_low=1405.0,
        baseband_bandwidth=64.0, baseband_sample_rate=128e6, dm=20.0,
        spectrum_channel_count=1 << 6,
        signal_detect_max_boxcar_length=16,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        baseband_reserve_sample=False)
    p_ref = SegmentProcessor(Config(**base))
    p_pal = SegmentProcessor(Config(**base, use_pallas=True,
                                    use_pallas_sk=True))
    wf_a, res_a = p_ref.process(raw)
    wf_b, res_b = p_pal.process(raw)
    assert np.asarray(res_a.signal_counts).shape == \
        np.asarray(res_b.signal_counts).shape == (2, 5)
    np.testing.assert_array_equal(np.asarray(res_a.zero_count),
                                  np.asarray(res_b.zero_count))
    np.testing.assert_allclose(np.asarray(res_a.time_series),
                               np.asarray(res_b.time_series),
                               rtol=1e-3, atol=1e-2)
    np.testing.assert_allclose(np.asarray(wf_a), np.asarray(wf_b),
                               rtol=1e-3, atol=1e-2)


def test_staged_matches_fused(synthetic_cfg):
    """The staged three-program plan (used for 2^30-class segments, with
    the chirp generated in-step) must reproduce the fused plan's output.
    The chirp differs by construction (host f64 bank vs in-trace df64),
    so tolerances are df64-level, not bitwise."""
    cfg = synthetic_cfg
    fused = SegmentProcessor(cfg)
    staged = SegmentProcessor(cfg, staged=True)
    assert staged.chirp is None  # no bank materialized
    raw = np.fromfile(cfg.input_file_path, dtype=np.uint8,
                      count=cfg.baseband_input_count)
    wf_f, res_f = fused.process(raw)
    wf_s, res_s = staged.process(raw)
    wf_f, wf_s = np.asarray(wf_f), np.asarray(wf_s)
    scale = np.abs(wf_f).max()
    np.testing.assert_allclose(wf_s, wf_f, atol=5e-3 * scale, rtol=0)
    assert np.array_equal(np.asarray(res_f.signal_counts),
                          np.asarray(res_s.signal_counts))
    ts_f = np.asarray(res_f.time_series)
    np.testing.assert_allclose(np.asarray(res_s.time_series), ts_f,
                               rtol=0, atol=5e-3 * np.abs(ts_f).max())


def test_staged_multistream_and_window(tmp_path):
    """Staged plan with a 2-stream interleaved format and a hann window:
    the window must be applied at unpack and de-applied after the
    waterfall C2C in stage (c), identically to the fused plan."""
    from srtb_tpu.io.synth import make_dispersed_baseband

    n = 1 << 16
    f_min, bw, dm = 1405.0, 64.0, 30.0
    one = make_dispersed_baseband(n, f_min, bw, dm,
                                  pulse_positions=n // 2, nbits=8)
    # byte-interleave two copies ("1212", ref: unpack.hpp:214-244)
    raw = np.empty(2 * n, dtype=np.uint8)
    raw[0::2] = one
    raw[1::2] = one
    cfg = Config(
        baseband_input_count=n,
        baseband_input_bits=8,
        baseband_format_type="interleaved_samples_2",
        baseband_freq_low=f_min,
        baseband_bandwidth=bw,
        baseband_sample_rate=128e6,
        dm=dm,
        spectrum_channel_count=1 << 7,
        signal_detect_signal_noise_threshold=6.0,
        baseband_reserve_sample=False,
    )
    fused = SegmentProcessor(cfg, window_name="hann")
    staged = SegmentProcessor(cfg, window_name="hann", staged=True)
    wf_f, res_f = fused.process(raw)
    wf_s, res_s = staged.process(raw)
    wf_f, wf_s = np.asarray(wf_f), np.asarray(wf_s)
    assert wf_f.shape[1] == 2  # two data streams
    scale = np.abs(wf_f).max()
    np.testing.assert_allclose(wf_s, wf_f, atol=5e-3 * scale, rtol=0)
    assert np.array_equal(np.asarray(res_f.signal_counts),
                          np.asarray(res_s.signal_counts))


def test_blocked_subbyte_strategies_and_staged_match():
    """Sub-byte simple-format segments run the fused blocked-plane R2C
    (ops/fft.rfft_subbyte: unpack + pack + FFT with no sample-order
    interleave).  Every strategy and the staged plan must agree with the
    classic monolithic path, window included."""
    from srtb_tpu.io.synth import make_dispersed_baseband

    n = 1 << 16
    f_min, bw, dm = 1405.0, 64.0, 30.0
    raw = make_dispersed_baseband(n, f_min, bw, dm,
                                  pulse_positions=n // 2, nbits=2)
    base = dict(
        baseband_input_count=n,
        baseband_input_bits=2,
        baseband_format_type="simple",
        baseband_freq_low=f_min,
        baseband_bandwidth=bw,
        baseband_sample_rate=128e6,
        dm=dm,
        spectrum_channel_count=1 << 7,
        signal_detect_signal_noise_threshold=6.0,
        baseband_reserve_sample=False,
    )
    ref = SegmentProcessor(Config(fft_strategy="monolithic", **base),
                           window_name="hann")
    assert ref._blocked_subbyte
    wf_ref, res_ref = ref.process(raw)
    wf_ref = np.asarray(wf_ref)
    scale = np.abs(wf_ref).max()
    variants = {
        "four_step": SegmentProcessor(
            Config(fft_strategy="four_step", **base), window_name="hann"),
        "mxu": SegmentProcessor(
            Config(fft_strategy="mxu", **base), window_name="hann"),
        "staged": SegmentProcessor(
            Config(fft_strategy="four_step", **base), window_name="hann",
            staged=True),
        "four_step+pallas": SegmentProcessor(
            Config(fft_strategy="four_step", use_pallas=True, **base),
            window_name="hann"),
    }
    for name, proc in variants.items():
        wf, res = proc.process(raw)
        np.testing.assert_allclose(
            np.asarray(wf), wf_ref, atol=5e-3 * scale, rtol=0,
            err_msg=name)
        assert np.array_equal(np.asarray(res.signal_counts),
                              np.asarray(res_ref.signal_counts)), name


def test_segment_deadline_fires_and_cancels(synthetic_cfg):
    """segment_deadline_s: the watchdog must fire on a wedged device sync
    and must NOT fire on a healthy one (cancel on success)."""
    import time as _time

    from srtb_tpu.pipeline.runtime import Pipeline

    cfg = synthetic_cfg.replace(segment_deadline_s=0.2,
                                writer_thread_count=0)
    p = Pipeline(cfg)
    fired = []
    p._on_segment_deadline = lambda: fired.append(True)
    # healthy: a fast fetch must not trip the timer
    assert p._sync_with_deadline(lambda: 42) == 42
    _time.sleep(0.3)
    assert not fired
    # wedged: a fetch slower than the deadline trips it
    p._sync_with_deadline(lambda: _time.sleep(0.4))
    assert fired
    p.close()


def test_staged_pallas_rows_impl_matches_default(monkeypatch):
    """SRTB_STAGED_ROWS_IMPL=pallas (the 2^30 SIGSEGV workaround
    candidate: Pallas leg FFTs instead of XLA's batched FFT) must
    produce the same staged-plan waterfall, blocked and classic.

    CPU-sized segments have four-step legs below pallas_fft.supported's
    2^12 minimum, so the kernel itself can't fire here (its numerics
    are pinned at supported sizes by tests/test_pallas_fft.py); this
    test asserts the *dispatch* — the env knob reaches _fft_minor as
    rows_impl='pallas_interpret' — plus numeric parity of the plan."""
    import numpy as np

    from srtb_tpu.config import Config
    from srtb_tpu.ops import fft as F
    from srtb_tpu.pipeline.segment import SegmentProcessor, \
        waterfall_to_numpy

    cfg = Config(
        baseband_input_count=1 << 14,
        baseband_input_bits=2,
        baseband_format_type="simple",
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        dm=30.0,
        spectrum_channel_count=1 << 5,
        mitigate_rfi_average_method_threshold=1e9,
        mitigate_rfi_spectral_kurtosis_threshold=1e9,
        baseband_reserve_sample=False,
    )
    rng = np.random.default_rng(9)
    raw = rng.integers(0, 256, cfg.segment_bytes(1), dtype=np.uint8)
    impls_seen = []
    orig = F._fft_minor

    def spy(x, inverse, rows_impl="xla", len_cap=None):
        impls_seen.append(rows_impl)
        return orig(x, inverse, rows_impl, len_cap)

    for blocked in ("0", "1"):
        monkeypatch.setenv("SRTB_STAGED_BLOCKED", blocked)
        monkeypatch.delenv("SRTB_STAGED_ROWS_IMPL", raising=False)
        base = waterfall_to_numpy(
            SegmentProcessor(cfg, staged=True).process(raw)[0])
        monkeypatch.setenv("SRTB_STAGED_ROWS_IMPL", "pallas")
        monkeypatch.setattr(F, "_fft_minor", spy)
        impls_seen.clear()
        got = waterfall_to_numpy(
            SegmentProcessor(cfg, staged=True).process(raw)[0])
        monkeypatch.setattr(F, "_fft_minor", orig)
        assert "pallas_interpret" in impls_seen, impls_seen
        np.testing.assert_allclose(got, base, rtol=2e-3, atol=2e-4)
    # a typo'd knob value must raise, not silently fall back to XLA
    monkeypatch.setenv("SRTB_STAGED_ROWS_IMPL", "palas")
    import pytest
    with pytest.raises(ValueError, match="rows impl"):
        SegmentProcessor(cfg, staged=True).process(raw)


def test_staged_pallas2_downgrades_below_window(monkeypatch):
    """SRTB_STAGED_ROWS_IMPL=pallas2 at a leg length below the fused
    two-pass window must downgrade to the pallas-legs four-step (and
    stay numerically on-plan), not crash a tiny forced-staged config."""
    import numpy as np

    from srtb_tpu.config import Config
    from srtb_tpu.pipeline.segment import SegmentProcessor, \
        waterfall_to_numpy

    cfg = Config(
        baseband_input_count=1 << 14,
        baseband_input_bits=2,
        baseband_format_type="simple",
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        dm=30.0,
        spectrum_channel_count=1 << 5,
        mitigate_rfi_average_method_threshold=1e9,
        mitigate_rfi_spectral_kurtosis_threshold=1e9,
        baseband_reserve_sample=False,
    )
    rng = np.random.default_rng(21)
    raw = rng.integers(0, 256, cfg.segment_bytes(1), dtype=np.uint8)
    monkeypatch.delenv("SRTB_STAGED_ROWS_IMPL", raising=False)
    base = waterfall_to_numpy(
        SegmentProcessor(cfg, staged=True).process(raw)[0])
    monkeypatch.setenv("SRTB_STAGED_ROWS_IMPL", "pallas2")
    proc = SegmentProcessor(cfg, staged=True)
    assert proc._staged_impl() == "pallas_interpret"
    got = waterfall_to_numpy(proc.process(raw)[0])
    np.testing.assert_allclose(got, base, rtol=2e-3, atol=2e-4)


def test_staged_pallas2_blocked_production_shape(monkeypatch):
    """The 2^30 production plan in miniature: blocked-plane sub-byte
    unpack + fused two-pass Pallas FFT legs across the staged (a)/(b)
    boundary, at the smallest in-window leg (n = 2^25, 4-bit, leg
    M = 2^24).  No XLA FFT op exists in stages a/b — the SIGSEGV
    workaround shape — and the waterfall must match the default staged
    plan."""
    import numpy as np

    from srtb_tpu.config import Config
    from srtb_tpu.pipeline.segment import SegmentProcessor, \
        waterfall_to_numpy

    cfg = Config(
        baseband_input_count=1 << 25,
        baseband_input_bits=4,
        baseband_format_type="simple",
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        dm=30.0,
        spectrum_channel_count=1 << 9,
        mitigate_rfi_average_method_threshold=1e9,
        mitigate_rfi_spectral_kurtosis_threshold=1e9,
        baseband_reserve_sample=False,
    )
    rng = np.random.default_rng(23)
    raw = rng.integers(0, 256, cfg.segment_bytes(1), dtype=np.uint8)
    monkeypatch.setenv("SRTB_STAGED_BLOCKED", "1")
    monkeypatch.delenv("SRTB_STAGED_ROWS_IMPL", raising=False)
    base = waterfall_to_numpy(
        SegmentProcessor(cfg, staged=True).process(raw)[0])
    monkeypatch.setenv("SRTB_STAGED_ROWS_IMPL", "pallas2")
    proc = SegmentProcessor(cfg, staged=True)
    assert proc._staged_impl() == "pallas2_interpret"
    got = waterfall_to_numpy(proc.process(raw)[0])
    np.testing.assert_allclose(got, base, rtol=2e-3, atol=2e-4)


def test_staged_pallas2_all_fusions_flagship(monkeypatch):
    """The queue's n2_30_pallas2_full combination in miniature: classic
    staged plan with fused two-pass legs PLUS the fused RFI/chirp front
    half and the fused waterfall/SK-stats epilogue in stage (c).  Every
    fusion on at once must stay on-plan against the plain staged run."""
    import numpy as np

    from srtb_tpu.config import Config
    from srtb_tpu.pipeline.segment import SegmentProcessor, \
        waterfall_to_numpy

    cfg = Config(
        baseband_input_count=1 << 25,
        baseband_input_bits=4,
        baseband_format_type="simple",
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        dm=30.0,
        spectrum_channel_count=1 << 9,
        mitigate_rfi_average_method_threshold=1e9,
        mitigate_rfi_spectral_kurtosis_threshold=1e9,
        baseband_reserve_sample=False,
    )
    rng = np.random.default_rng(29)
    raw = rng.integers(0, 256, cfg.segment_bytes(1), dtype=np.uint8)
    monkeypatch.delenv("SRTB_STAGED_ROWS_IMPL", raising=False)
    monkeypatch.delenv("SRTB_STAGED_BLOCKED", raising=False)
    base = waterfall_to_numpy(
        SegmentProcessor(cfg, staged=True).process(raw)[0])
    monkeypatch.setenv("SRTB_STAGED_ROWS_IMPL", "pallas2")
    proc = SegmentProcessor(
        cfg.replace(use_pallas=True, use_pallas_sk=True), staged=True)
    assert proc._staged_impl() == "pallas2_interpret"
    got = waterfall_to_numpy(proc.process(raw)[0])
    np.testing.assert_allclose(got, base, rtol=2e-3, atol=2e-4)


@pytest.mark.slow  # pallas2-interpret compile of the 2^26 leg: ~3-4 min
def test_staged_pallas2_blocked_2bit_production_format(monkeypatch):
    """The staged_blocked_pallas2 queue probe's exact composition in
    miniature: 2-bit blocked planes (p = 2 packed plane pairs, the
    J1644 production format) with fused two-pass legs across the staged
    (a)/(b) boundary, at the smallest in-window leg (n = 2^26,
    M = n/4 = 2^24 per plane)."""
    import numpy as np

    from srtb_tpu.config import Config
    from srtb_tpu.pipeline.segment import SegmentProcessor, \
        waterfall_to_numpy

    cfg = Config(
        baseband_input_count=1 << 26,
        baseband_input_bits=2,
        baseband_format_type="simple",
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        dm=30.0,
        spectrum_channel_count=1 << 10,
        mitigate_rfi_average_method_threshold=1e9,
        mitigate_rfi_spectral_kurtosis_threshold=1e9,
        baseband_reserve_sample=False,
    )
    rng = np.random.default_rng(37)
    raw = rng.integers(0, 256, cfg.segment_bytes(1), dtype=np.uint8)
    monkeypatch.setenv("SRTB_STAGED_BLOCKED", "1")
    monkeypatch.delenv("SRTB_STAGED_ROWS_IMPL", raising=False)
    base = waterfall_to_numpy(
        SegmentProcessor(cfg, staged=True).process(raw)[0])
    monkeypatch.setenv("SRTB_STAGED_ROWS_IMPL", "pallas2")
    proc = SegmentProcessor(cfg, staged=True)
    assert proc._staged_impl() == "pallas2_interpret"
    got = waterfall_to_numpy(proc.process(raw)[0])
    np.testing.assert_allclose(got, base, rtol=2e-3, atol=2e-4)
