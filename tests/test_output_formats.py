"""Byte-level validation of the candidate files the pipeline writes.

A reference user's downstream tooling parses these exact layouts (the
reference writes .npy via cnpy::npy_save and .tim as raw float32 —
ref: pipeline/write_signal_pipe.hpp:225-280 — and .bin as the raw
segment bytes), so the bytes on disk are API surface.  These tests
parse the files with an independent decoder (struct/ast, not np.load)
and check every header field and payload byte."""

import ast
import struct

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.pipeline.runtime import Pipeline


@pytest.fixture(scope="module")
def written(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fmt")
    n = 1 << 14
    cfg = Config(
        baseband_input_count=n,
        baseband_input_bits=2,
        baseband_format_type="simple",
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        dm=30.0,
        input_file_path=str(tmp / "bb.bin"),
        baseband_output_file_prefix=str(tmp / "out_"),
        spectrum_channel_count=1 << 5,
        signal_detect_signal_noise_threshold=5.0,
        signal_detect_max_boxcar_length=16,
        mitigate_rfi_average_method_threshold=1e9,
        mitigate_rfi_spectral_kurtosis_threshold=1e9,
        baseband_reserve_sample=False,
    )
    make_dispersed_baseband(
        n, cfg.baseband_freq_low, cfg.baseband_bandwidth, cfg.dm,
        pulse_positions=n // 2, pulse_amp=30.0, nbits=2,
    ).tofile(cfg.input_file_path)
    pipe = Pipeline(cfg)
    pipe.run()
    assert pipe.sinks[0].written
    return cfg, pipe.sinks[0].written[0]


def test_npy_bytes_are_spec_exact(written):
    """Parse the .npy with struct/ast only (NPY format 1.0 as
    cnpy::npy_save emits it): magic, version, little-endian complex64
    descr, C order, (channels, wlen) shape, then exactly
    shape-product * 8 payload bytes."""
    cfg, rec = written
    raw = open(rec.npy_paths[0], "rb").read()
    assert raw[:6] == b"\x93NUMPY"
    major, minor = raw[6], raw[7]
    assert (major, minor) == (1, 0)
    (hlen,) = struct.unpack("<H", raw[8:10])
    assert (10 + hlen) % 64 == 0  # spec: header pads to 64-byte alignment
    header = ast.literal_eval(raw[10:10 + hlen].decode("latin1").strip())
    assert header["descr"] == "<c8"
    assert header["fortran_order"] is False
    ch = cfg.spectrum_channel_count
    wlen = cfg.baseband_input_count // 2 // ch
    assert header["shape"] == (ch, wlen)
    payload = raw[10 + hlen:]
    assert len(payload) == ch * wlen * 8
    # and the payload really is the waterfall np.load sees
    wf = np.frombuffer(payload, dtype="<c8").reshape(ch, wlen)
    np.testing.assert_array_equal(wf, np.load(rec.npy_paths[0]))


def test_tim_bytes_are_raw_f32_per_boxcar(written):
    """.tim payload: raw little-endian float32 (the reference writes the
    bare sample buffer, write_signal_pipe.hpp:250-280), one file per
    boxcar length, named <base>.<boxcar>.tim, with the boxcar-L sliding
    difference's valid length (T for L=1, T-L otherwise; the writer trims
    the zero-padded tail of the static-shape device rows)."""
    cfg, rec = written
    wlen = cfg.baseband_input_count // 2 // cfg.spectrum_channel_count
    assert rec.tim_paths
    for path in rec.tim_paths:
        stem = path.rsplit(".", 2)
        boxcar = int(stem[1])
        raw = open(path, "rb").read()
        assert len(raw) % 4 == 0
        ts = np.frombuffer(raw, dtype="<f4")
        expect = wlen if boxcar == 1 else wlen - boxcar
        assert ts.size == expect, (path, ts.size)
        assert np.isfinite(ts).all()


def test_bin_is_raw_segment_bytes(written):
    """.bin: the segment's raw input bytes, verbatim (reserve disabled
    here, so the full segment)."""
    cfg, rec = written
    raw = open(rec.bin_path, "rb").read()
    src = open(cfg.input_file_path, "rb").read()
    seg_bytes = cfg.segment_bytes(1)
    assert len(raw) == seg_bytes
    assert raw == src[:seg_bytes]
