"""Utility-layer tests: buffer pool (cached-allocator semantics), metrics,
running-mean quantizer vs oracle, termination handler install."""

import jax.numpy as jnp
import numpy as np

from srtb_tpu.ops import running_mean as rm
from srtb_tpu.utils.bufferpool import BufferPool
from srtb_tpu.utils.metrics import Metrics
from srtb_tpu.utils.termination import install_termination_handler


def test_buffer_pool_reuse():
    pool = BufferPool("test")
    a = pool.acquire(1024)
    assert a.nbytes == 1024 and a.dtype == np.uint8
    base_id = id(a.base if a.base is not None else a)
    pool.release(a)
    b = pool.acquire(1000)  # within the 0.5 threshold -> reuse
    assert id(b.base if b.base is not None else b) == base_id
    pool.release(b)
    c = pool.acquire(256)  # too small a request for the cached 1024 block
    assert id(c.base if c.base is not None else c) != base_id
    pool.release(c)
    assert pool.free_all() == 0


def test_buffer_pool_leak_detection():
    pool = BufferPool("leak")
    a = pool.acquire(64)
    assert pool.free_all() == 1
    pool.release(a)  # unknown now; warns, no crash


def test_metrics():
    m = Metrics()
    m.add("samples", 1e6)
    m.add("samples", 1e6)
    m.add("packets_total", 100)
    m.add("packets_lost", 3)
    snap = m.snapshot()
    assert snap["samples"] == 2e6
    assert abs(snap["packet_loss_rate"] - 0.03) < 1e-12
    assert "msamples_per_sec" in snap
    assert isinstance(m.to_json(), str)


def test_running_mean_vs_oracle():
    rng = np.random.default_rng(0)
    nsamp, nchan, window = 64, 8, 16
    data = rng.integers(0, 100, size=(nsamp, nchan)).astype(np.float32)
    ave0 = np.asarray(rm.running_mean_init_average(jnp.asarray(data), window))
    expected_ave0 = data[:window].mean(axis=0)
    np.testing.assert_allclose(ave0, expected_ave0, rtol=1e-5)

    out, ave = rm.running_mean(jnp.asarray(data), window,
                               jnp.asarray(ave0))
    out_o, ave_o = rm.running_mean_oracle(data, window, expected_ave0)
    np.testing.assert_array_equal(np.asarray(out), out_o)
    np.testing.assert_allclose(np.asarray(ave), ave_o, rtol=1e-4)


def test_termination_handler_idempotent():
    install_termination_handler()
    install_termination_handler()  # no crash on double install


def test_http_metrics_endpoint(tmp_path):
    """/metrics (Prometheus text) and /metrics.json on the waterfall HTTP
    server expose the runtime counters (beyond the reference's log-only
    observability, SURVEY.md §5.5)."""
    import json
    import urllib.request

    from srtb_tpu.gui.server import WaterfallHTTPServer
    from srtb_tpu.utils.metrics import metrics

    metrics.reset()
    metrics.add("segments", 3)
    metrics.add("samples", 1000)
    server = WaterfallHTTPServer(str(tmp_path), port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        text = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "srtb_segments 3" in text
        snap = json.loads(
            urllib.request.urlopen(base + "/metrics.json").read())
        assert snap["segments"] == 3
        assert "elapsed_s" in snap
    finally:
        server.stop()
        metrics.reset()  # don't leak counter state into other tests


def test_waterfall_server_interactive_surface(tmp_path):
    """The interactive viewer's JSON frame feed and page controls: the
    QML-window replacement (ref: gui.hpp:34-67, main.qml:14-28) must
    expose the frame history for the scrubber and the control bar."""
    import json
    import urllib.request

    from srtb_tpu.gui.server import WaterfallHTTPServer

    for idx in range(3):
        (tmp_path / f"waterfall_s0_{idx:06d}.png").write_bytes(
            b"\x89PNG\r\n\x1a\nstub")
    (tmp_path / "waterfall_s1_000000.png").write_bytes(
        b"\x89PNG\r\n\x1a\nstub")
    srv = WaterfallHTTPServer(str(tmp_path)).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        feed = json.loads(
            urllib.request.urlopen(base + "/frames.json").read())
        assert feed["streams"]["0"] == [
            f"waterfall_s0_{i:06d}.png" for i in range(3)]
        assert feed["streams"]["1"] == ["waterfall_s1_000000.png"]
        page = urllib.request.urlopen(base + "/").read().decode()
        # latest frame inlined per stream + the interactive controls
        assert "waterfall_s0_000002.png" in page
        assert 'id="pane1"' in page
        for control in ("pause", "zin", "bright", "contrast",
                        "frames.json"):
            assert control in page, control
    finally:
        srv.stop()
