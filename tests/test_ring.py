"""Device-resident overlap-save ring tests (ISSUE 8 acceptance).

- incremental (ring on) vs full-upload (ring off) output parity is
  BIT-identical across plan families (monolithic / four_step+ftail /
  staged / micro-batch) and both sources (file + UDP);
- per-segment ``h2d_bytes`` follows the stride model exactly: one cold
  full-segment upload, then stride_bytes per warm dispatch;
- carry invalidation: watchdog requeue, checkpoint resume, and broken
  stream adjacency (a dropped/interleaved segment upstream) all force a
  cold re-arm and stay bit-identical;
- the staging-buffer pool reuses one host block across micro-batches;
- the checked-in plan cards prove the carry donation is a real alias
  (``aliased``, never ``dropped``/``no_candidate``) for every ring-v1
  warm assemble program.
"""

import json
import os
import socket
import struct
import threading
import time

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.io import formats, udp
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.pipeline.runtime import Pipeline
from srtb_tpu.pipeline.segment import SegmentProcessor
from srtb_tpu.utils.metrics import metrics

N = 1 << 14  # 16384 samples, 8-bit: segment_bytes == N


@pytest.fixture(scope="module")
def synth_file(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("ring")
    data = make_dispersed_baseband(N * 4, 1405.0, 64.0, 0.05,
                                   pulse_positions=N, nbits=8)
    path = str(tmp / "bb.bin")
    data.tofile(path)
    return path


def _cfg(path, tmp_path, tag, **extra):
    kw = dict(
        baseband_input_count=N,
        baseband_input_bits=8,
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        dm=0.05,  # reserves 2304 of 16384 bytes (~14%)
        input_file_path=path,
        baseband_output_file_prefix=str(tmp_path / f"{tag}_"),
        spectrum_channel_count=64,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        signal_detect_max_boxcar_length=64,
        baseband_reserve_sample=True,
        writer_thread_count=0,
        inflight_segments=3)
    kw.update(extra)
    return Config(**kw)


class _CaptureSink:
    def __init__(self):
        self.out = []

    def push(self, work, positive):
        det = work.detect
        self.out.append((np.asarray(det.signal_counts).copy(),
                         np.asarray(det.zero_count).copy(),
                         np.asarray(det.time_series).copy()))


def _assert_same(a_sink, b_sink):
    assert len(a_sink.out) == len(b_sink.out) > 0
    for a, b in zip(a_sink.out, b_sink.out):
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)


def _run(cfg, processor=None, max_segments=None):
    metrics.reset()
    sink = _CaptureSink()
    with Pipeline(cfg, sinks=[sink], processor=processor) as pipe:
        stats = pipe.run(max_segments=max_segments)
    got = (stats, sink, metrics.get("h2d_bytes"),
           metrics.get("ring_cold_dispatches"), pipe.processor)
    metrics.reset()
    return got


# ------------------------------------------------------ ring resolution


def test_ring_resolution():
    base = dict(baseband_input_count=N, baseband_input_bits=8,
                baseband_freq_low=1405.0, baseband_bandwidth=64.0,
                baseband_sample_rate=128e6, spectrum_channel_count=64)
    on = SegmentProcessor(Config(dm=0.05, baseband_reserve_sample=True,
                                 **base))
    assert on.ring and 0 < on.reserved_bytes < on.stride_bytes
    assert on.plan_name.endswith("+ring")
    assert '"ingest": "ring-v1"' in on.plan_signature()
    off = SegmentProcessor(Config(dm=0.05, baseband_reserve_sample=True,
                                  ingest_ring="off", **base))
    assert not off.ring and '"ingest": "direct"' in off.plan_signature()
    # no reserved tail -> auto resolves off; "on" is a loud error
    none = SegmentProcessor(Config(baseband_reserve_sample=False, **base))
    assert not none.ring
    with pytest.raises(ValueError, match="ingest_ring=on"):
        SegmentProcessor(Config(baseband_reserve_sample=False,
                                ingest_ring="on", **base))
    with pytest.raises(ValueError, match="auto/on/off"):
        SegmentProcessor(Config(ingest_ring="maybe", **base))
    # ring methods refuse on a non-ring plan
    with pytest.raises(ValueError, match="ring disabled"):
        none.run_device_cold(np.zeros(N, np.uint8))
    with pytest.raises(ValueError, match="stride_only"):
        none.stage_input(np.zeros(N, np.uint8), stride_only=True)


# ------------------------------------------- incremental-vs-full parity


@pytest.mark.parametrize("plan", ["monolithic", "four_step", "staged",
                                  "micro_batch"])
def test_incremental_vs_full_upload_bit_identical(synth_file, tmp_path,
                                                  plan):
    """Ring on vs off must change H2D bytes only — never one output
    bit — and the h2d_bytes counter must follow the stride model
    exactly (full segment on the one cold dispatch, stride after)."""
    extra = {}
    staged = None
    if plan == "monolithic":
        extra = dict(fft_strategy="monolithic", fused_tail="off")
    elif plan == "four_step":
        extra = dict(fft_strategy="four_step", fused_tail="on")
    elif plan == "staged":
        staged = True
    elif plan == "micro_batch":
        extra = dict(micro_batch_segments=2, inflight_segments=4)
    outs = {}
    for ring in ("auto", "off"):
        cfg = _cfg(synth_file, tmp_path, f"{plan}_{ring}",
                   ingest_ring=ring, **extra)
        proc = None
        if staged:
            proc = SegmentProcessor(cfg, staged=True)
        outs[ring] = _run(cfg, processor=proc)
    stats, sink_on, h_on, cold_on, proc = outs["auto"]
    _, sink_off, h_off, cold_off, _ = outs["off"]
    _assert_same(sink_on, sink_off)
    nseg = stats.segments
    seg_b, stride = proc._segment_bytes, proc.stride_bytes
    assert h_off == nseg * seg_b and cold_off == 0
    if plan == "micro_batch":
        # one cold batch (2 full segments), then strides
        assert h_on == 2 * seg_b + (nseg - 2) * stride
    else:
        assert h_on == seg_b + (nseg - 1) * stride
    assert cold_on == 1
    # the ring saved exactly the reserved fraction on warm dispatches
    assert h_off - h_on == (nseg - (2 if plan == "micro_batch" else 1)) \
        * proc.reserved_bytes


def test_serial_window_and_sanitizer_ring(synth_file, tmp_path):
    """inflight_segments=1 (serial) and Config.sanitize both run the
    ring path unchanged: same outputs, same stride model."""
    ref = _run(_cfg(synth_file, tmp_path, "ref", ingest_ring="off"))
    ser = _run(_cfg(synth_file, tmp_path, "ser", inflight_segments=1))
    san = _run(_cfg(synth_file, tmp_path, "san", inflight_segments=2,
                    sanitize=True))
    _assert_same(ser[1], ref[1])
    _assert_same(san[1], ref[1])
    for stats, _, h2d, cold, proc in (ser, san):
        assert h2d == proc._segment_bytes \
            + (stats.segments - 1) * proc.stride_bytes
        assert cold == 1


# ------------------------------------------------- telemetry accounting


def test_journal_h2d_accounting(synth_file, tmp_path):
    """Journal spans carry cumulative h2d_bytes: consecutive deltas
    localize the stride model per segment."""
    from srtb_tpu.tools import telemetry_report as TR

    cfg = _cfg(synth_file, tmp_path, "jrnl",
               telemetry_journal_path=str(tmp_path / "jrnl.jsonl"))
    stats, _, h2d, _, proc = _run(cfg)
    recs = TR.load(cfg.telemetry_journal_path)
    assert len(recs) == stats.segments
    assert recs[-1]["h2d_bytes"] == h2d
    assert h2d == proc._segment_bytes \
        + (stats.segments - 1) * proc.stride_bytes
    assert all(r["ring_cold_dispatches"] == 1 for r in recs)
    deltas = [b["h2d_bytes"] - a["h2d_bytes"]
              for a, b in zip(recs, recs[1:])]
    # dispatch runs AHEAD of drain inside the window, so a record's
    # delta covers 0..W warm strides — but only whole strides (the one
    # cold full segment is the first record's base), monotonically
    assert all(d >= 0 and d % proc.stride_bytes == 0 for d in deltas)


# ------------------------------------------------------------- sources


def _udp_cfg(port, **extra):
    kw = dict(baseband_input_count=16384, baseband_input_bits=8,
              baseband_format_type="fastmb_roach2",
              baseband_freq_low=1405.0, baseband_bandwidth=64.0,
              baseband_sample_rate=128e6, dm=0.05,
              spectrum_channel_count=2048,
              mitigate_rfi_average_method_threshold=100.0,
              mitigate_rfi_spectral_kurtosis_threshold=2.0,
              udp_receiver_address=["127.0.0.1"],
              udp_receiver_port=[port],
              baseband_reserve_sample=True,
              writer_thread_count=0, inflight_segments=2)
    kw.update(extra)
    return Config(**kw)


def _send_packets(port, count, delay=0.002):
    fmt = formats.FASTMB_ROACH2
    payload = fmt.payload_bytes
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    time.sleep(0.1)
    rng = np.random.default_rng(7)
    blobs = [rng.integers(0, 256, size=payload, dtype=np.uint8).tobytes()
             for _ in range(count)]
    for c in range(count):
        sock.sendto(struct.pack("<Q", c) + blobs[c], ("127.0.0.1", port))
        time.sleep(delay)
    sock.close()


def test_udp_source_overlap_assembly():
    """The real-time source overlaps consecutive segments by the
    reserved tail (stride receives + retained-tail head), with the
    packet counter stamped for the segment's FIRST byte."""
    port = 43310
    cfg = _udp_cfg(port)
    src = udp.UdpReceiverSource(cfg, use_native=False)
    payload = formats.FASTMB_ROACH2.payload_bytes
    assert src.reserved_bytes == payload and src.stride_bytes == 3 * payload
    t = threading.Thread(target=_send_packets, args=(port, 8))
    t.start()
    seg1, seg2 = next(src), next(src)
    t.join()
    src.close()
    np.testing.assert_array_equal(seg2.data[:payload],
                                  seg1.data[-payload:])
    assert seg1.udp_packet_counter == 0 and seg2.udp_packet_counter == 3
    assert (seg1.seq, seg2.seq) == (0, 1)


def test_udp_misaligned_stride_degrades_to_legacy_framing():
    """A reserved tail whose stride is not a payload multiple must NOT
    fail startup: the source keeps the legacy non-overlapping block
    framing (warned) and leaves seq unstamped so the engine's
    adjacency guard keeps the ring cold — never warm-assembles
    non-overlapping blocks against a foreign carry."""
    port = 43340
    # channels=512 -> reserved rounds to 1024-sample tiles: stride is
    # a 1024 multiple but not a 4096 (payload) multiple
    cfg = _udp_cfg(port, spectrum_channel_count=512)
    src = udp.UdpReceiverSource(cfg, use_native=False)
    assert src.reserved_bytes == 0  # overlap disabled, not fatal
    assert src.stride_bytes == src.segment_bytes
    t = threading.Thread(target=_send_packets, args=(port, 8))
    t.start()
    seg1, seg2 = next(src), next(src)
    t.join()
    src.close()
    assert (seg1.seq, seg2.seq) == (-1, -1)  # never warm-assembled
    # legacy framing: consecutive full blocks, no overlap
    assert seg2.udp_packet_counter == 4


def test_staged_ring_sanitize_expires_carry(synth_file, tmp_path):
    """Under Config.sanitize the staged ring's ALWAYS-donated carry is
    expired even with donate_input=False (the CPU-CI stand-in for the
    TPU's donated-buffer invalidation): reusing a consumed carry
    raises instead of silently passing on CPU."""
    cfg = _cfg(synth_file, tmp_path, "sanc", sanitize=True)
    proc = SegmentProcessor(cfg, staged=True)
    raw = np.fromfile(synth_file, dtype=np.uint8, count=N)
    from srtb_tpu.analysis.sanitizer import Sanitizer
    san = Sanitizer()
    with san.run_scope():
        _, c1 = proc.run_device_cold(proc.stage_input(raw))
        new = proc.stage_input(raw, stride_only=True)
        _, c2 = proc.run_device_ring(c1, new)
        with pytest.raises(Exception, match="[Dd]onat|[Dd]elet"):
            proc.run_device_ring(c1, proc.stage_input(
                raw, stride_only=True))  # c1 was consumed


def test_udp_incremental_vs_full_upload_bit_identical(tmp_path):
    """Engine parity on the real-time source: same packet stream, ring
    on vs off, bit-identical detections + the stride H2D model."""
    outs = {}
    for i, ring in enumerate(("auto", "off")):
        port = 43320 + i
        cfg = _udp_cfg(port, ingest_ring=ring,
                       baseband_output_file_prefix=str(
                           tmp_path / f"udp_{ring}_"))
        src = udp.UdpReceiverSource(cfg, use_native=False)
        t = threading.Thread(target=_send_packets, args=(port, 12))
        t.start()
        metrics.reset()
        sink = _CaptureSink()
        with Pipeline(cfg, source=src, sinks=[sink]) as pipe:
            stats = pipe.run(max_segments=3)
        t.join()
        src.close()
        outs[ring] = (stats, sink, metrics.get("h2d_bytes"),
                      metrics.get("ring_cold_dispatches"),
                      pipe.processor)
        metrics.reset()
    _assert_same(outs["auto"][1], outs["off"][1])
    _, _, h_on, cold_on, proc = outs["auto"]
    assert cold_on == 1
    assert h_on == proc._segment_bytes + 2 * proc.stride_bytes
    assert outs["off"][2] == 3 * proc._segment_bytes


# --------------------------------------------------- carry invalidation


class _FlakyReady(Pipeline):
    """Readiness probe that reports the drain head unready until the
    watchdog has requeued once — a deterministic compute wedge."""

    def _result_ready(self, det_res):
        if metrics.get("watchdog_requeues") < 1:
            return False
        return Pipeline._result_ready(det_res)


def test_watchdog_requeue_goes_cold_bit_identical(synth_file, tmp_path):
    """A watchdog requeue re-dispatches cold from the retained host
    buffer AND invalidates the live carry (the wedged device may never
    materialize it); outputs stay bit-identical."""
    ref = _run(_cfg(synth_file, tmp_path, "wd_ref", ingest_ring="off"))
    metrics.reset()
    cfg = _cfg(synth_file, tmp_path, "wd", inflight_segments=2,
               segment_deadline_s=0.15, segment_watchdog_requeues=2,
               retry_backoff_base_s=0.001)
    sink = _CaptureSink()
    with _FlakyReady(cfg, sinks=[sink]) as pipe:
        stats = pipe.run()
    h2d = metrics.get("h2d_bytes")
    cold = metrics.get("ring_cold_dispatches")
    assert metrics.get("watchdog_requeues") == 1
    metrics.reset()
    _assert_same(sink, ref[1])
    proc = pipe.processor
    # cold dispatches: segment 0's initial dispatch, its requeue, and
    # the first fresh dispatch after the invalidation; everything
    # later re-warms off the re-armed carry.  Segment 1 was warm-
    # dispatched BEFORE the wedge (window 2), so warm uploads cover
    # all but two segments — plus the one extra full upload of the
    # requeued segment itself.
    assert cold == 3
    assert h2d == 3 * proc._segment_bytes \
        + (stats.segments - 2) * proc.stride_bytes


def test_checkpoint_resume_goes_cold_bit_identical(synth_file, tmp_path):
    """A resumed run has no device carry: its first dispatch is a cold
    full upload from the checkpointed offset, and the stitched output
    stream is bit-identical to an uninterrupted ring run."""
    ref = _run(_cfg(synth_file, tmp_path, "ck_ref", ingest_ring="off"))
    cfg = _cfg(synth_file, tmp_path, "ck",
               checkpoint_path=str(tmp_path / "ck.json"))
    first = _run(cfg, max_segments=2)
    assert first[0].segments == 2
    resumed = _run(cfg)
    assert resumed[3] == 1  # ONE cold dispatch: the resume re-arm
    stitched = _CaptureSink()
    stitched.out = first[1].out + resumed[1].out
    _assert_same(stitched, ref[1])


class _SeqGapSource:
    """Wraps a source but breaks SegmentWork.seq adjacency — the
    upstream signature of a dropped or interleaved segment."""

    def __init__(self, inner):
        self.inner = inner
        self.pool = getattr(inner, "pool", None)

    def __iter__(self):
        return self

    def __next__(self):
        seg = next(self.inner)
        seg.seq = seg.seq * 2  # gap after the first segment
        return seg

    @property
    def logical_offset(self):
        return getattr(self.inner, "logical_offset", 0)


def test_broken_adjacency_goes_cold_never_wrong(synth_file, tmp_path):
    """Segments that are not stream-adjacent (seq gaps) must NEVER be
    warm-assembled against a foreign carry: every dispatch after a gap
    goes cold, and the outputs match the full-upload reference."""
    ref = _run(_cfg(synth_file, tmp_path, "gap_ref", ingest_ring="off"))
    metrics.reset()
    cfg = _cfg(synth_file, tmp_path, "gap")
    from srtb_tpu.io.file_input import BasebandFileReader
    src = _SeqGapSource(BasebandFileReader(cfg))
    sink = _CaptureSink()
    with Pipeline(cfg, source=src, sinks=[sink]) as pipe:
        stats = pipe.run()
    cold = metrics.get("ring_cold_dispatches")
    h2d = metrics.get("h2d_bytes")
    metrics.reset()
    _assert_same(sink, ref[1])
    # seq 0 anchors seq... 0*2=0; 1->2, 2->4: nothing adjacent after
    # the first pair check, so every dispatch is a full upload
    assert cold == stats.segments
    assert h2d == stats.segments * pipe.processor._segment_bytes


# ------------------------------------------------- staging-buffer pool


def test_staging_pool_reuses_micro_batch_blocks(synth_file, tmp_path):
    """Micro-batch stacking draws from the processor's staging pool
    (one cached block reused per batch shape) instead of allocating a
    fresh np.stack per batch, and drains return every block."""
    cfg = _cfg(synth_file, tmp_path, "pool", micro_batch_segments=2,
               inflight_segments=4)
    stats, _, _, _, proc = _run(cfg)
    assert stats.segments >= 4
    pool = proc._staging_pool.stats()
    assert pool["in_use"] == 0
    # two distinct block sizes at most: [B, seg] (cold) + [B, stride]
    assert 1 <= pool["cached_blocks"] <= 2
    assert not proc._staging_out  # all registrations released


def test_staging_copy_path_and_release():
    """stage_input copies non-contiguous/non-uint8 input into a pooled
    block, registers it against the owner, and release_staging returns
    it; contiguous uint8 input never touches the pool."""
    cfg = Config(baseband_input_count=N, baseband_input_bits=8,
                 baseband_freq_low=1405.0, baseband_bandwidth=64.0,
                 baseband_sample_rate=128e6, dm=0.05,
                 spectrum_channel_count=64, baseband_reserve_sample=True)
    proc = SegmentProcessor(cfg)
    clean = np.zeros(N, np.uint8)
    proc.stage_input(clean)
    assert proc._staging_pool.stats()["in_use"] == 0  # no copy needed
    strided = np.zeros(2 * N, np.uint8)[::2]  # non-contiguous view
    proc.stage_input(strided)
    assert proc._staging_pool.stats()["in_use"] == 1
    proc.release_staging(strided)
    st = proc._staging_pool.stats()
    assert st["in_use"] == 0 and st["cached_blocks"] == 1


def test_staging_overflow_cap_self_heals():
    """Callers that never release (direct API users) are reclaimed by
    the FIFO cap instead of leaking one block per call."""
    cfg = Config(baseband_input_count=N, baseband_input_bits=8,
                 baseband_freq_low=1405.0, baseband_bandwidth=64.0,
                 baseband_sample_rate=128e6, dm=0.05,
                 spectrum_channel_count=64, baseband_reserve_sample=True)
    proc = SegmentProcessor(cfg)
    owners = [np.zeros(2 * N, np.uint8)[::2] for _ in range(20)]
    for o in owners:
        proc.stage_input(o)
    assert len(proc._staging_out) <= proc._staging_cap
    assert proc._staging_pool.stats()["in_use"] <= proc._staging_cap


# ------------------------------------------------- plan-audit coverage


def test_checked_in_cards_prove_carry_alias():
    """The committed plan_cards.json baseline cards every ring-v1
    family with the carry donation PROVEN aliased on each warm
    assemble program (never dropped / no_candidate)."""
    from srtb_tpu.analysis import hlo_audit as HA

    with open(HA.DEFAULT_BASELINE) as f:
        data = json.load(f)
    ring_cards = {k: c for k, c in data["cards"].items()
                  if c.get("ingest") == "ring-v1"}
    assert set(ring_cards) >= {"four_step_ftail_ring", "monolithic_ring",
                               "pallas_skzap_ring", "staged_ring",
                               "four_step_ftail_ring_mb2"}
    for key, card in ring_cards.items():
        warm = {n: p for n, p in card["programs"].items()
                if n in ("ring", "stage_a_ring", "batch_ring")}
        assert warm, key
        for name, prog in warm.items():
            don = prog["donation"]
            assert 0 in don["aliased"], (key, name, don)
            assert 0 not in don["dropped"] + don["no_candidate"]
            assert prog["alias_bytes"] == card["reserved_bytes"] > 0
        assert card["checks"]["ring_alias_ok"], key
    # direct-ingest families are untouched by the ring machinery
    assert data["cards"]["four_step_ftail"]["ingest"] == "direct"


def test_live_audit_proves_alias_and_catches_loss():
    """One live lowering: the ring family audits ring_alias_ok, and a
    non-donating assemble wrapper visibly loses the alias (the
    regression the ci gate guards)."""
    import jax

    from srtb_tpu.analysis import hlo_audit as HA

    cards = HA.audit_families(["four_step_ftail_ring"])
    card = cards["four_step_ftail_ring"]
    assert card["checks"]["ring_alias_ok"]
    assert card["checks"]["declared_matches_family"]
    spec = next(s for s in HA.PLAN_FAMILIES
                if s.key == "four_step_ftail_ring")
    proc = HA.build_plan(spec)
    (_, _, args, _), = [p for p in proc.lowerables() if p[0] == "ring"]
    lost = HA.audit_program(jax.jit(proc._process_ring), args, (),
                            8 * proc.n_spectrum)
    assert 0 not in lost["donation"]["aliased"]


# --------------------------------------------------------- AOT + reader


def test_aot_cache_covers_ring_programs(synth_file, tmp_path):
    """enable_aot persists the ring programs too: a warm restart loads
    cold+warm executables and produces identical results."""
    cfg = _cfg(synth_file, tmp_path, "aot")
    raw = np.fromfile(synth_file, dtype=np.uint8, count=N)
    proc1 = SegmentProcessor(cfg)
    assert proc1.enable_aot(str(tmp_path / "aot"), allow_cpu=True)
    (wf1, det1), c1 = proc1.run_device_cold(proc1.stage_input(raw))
    proc2 = SegmentProcessor(cfg)
    assert proc2.enable_aot(str(tmp_path / "aot"), allow_cpu=True)
    (wf2, det2), c2 = proc2.run_device_cold(proc2.stage_input(raw))
    np.testing.assert_array_equal(np.asarray(wf1), np.asarray(wf2))
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))
    names = {p.name for p in (tmp_path / "aot").iterdir()}
    assert any("ring" in n for n in names), names


def test_file_reader_skip_read_bit_identical(synth_file, tmp_path):
    """The skip-read fast path (stride reads + retained tail) emits the
    exact byte stream and logical offsets of the legacy seek-back
    path, while reading only stride bytes from disk per warm segment."""
    from srtb_tpu.io.file_input import BasebandFileReader
    from srtb_tpu.utils.bufferpool import BufferPool

    def harvest(ring):
        cfg = _cfg(synth_file, tmp_path, "rd", ingest_ring=ring)
        metrics.reset()
        r = BasebandFileReader(cfg, buffer_pool=BufferPool("t"))
        segs = [(s.data.copy(), r.logical_offset, s.seq) for s in r]
        bytes_read = metrics.get("file_bytes_read")
        metrics.reset()
        r.close()
        return segs, bytes_read, r

    fast, fast_bytes, r = harvest("auto")
    legacy, legacy_bytes, _ = harvest("off")
    assert len(fast) == len(legacy)
    for (a, oa, sa), (b, ob, sb) in zip(fast, legacy):
        np.testing.assert_array_equal(a, b)
        assert oa == ob and sa == sb
    # the fast path never re-reads the reserved tail from disk
    assert legacy_bytes - fast_bytes == (len(fast) - 1) * r.reserved_bytes
