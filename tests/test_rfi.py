"""RFI mitigation tests (oracle style follows test-rfi_mitigation.cpp:
range parsing + end-state of zapped bins, plus numpy recomputation)."""

import jax.numpy as jnp
import numpy as np

from srtb_tpu.ops import rfi


def test_eval_rfi_ranges():
    ranges = rfi.eval_rfi_ranges("11-12, 15-90, 233-235, 1176-1177")
    assert ranges == [(11.0, 12.0), (15.0, 90.0), (233.0, 235.0),
                      (1176.0, 1177.0)]
    assert rfi.eval_rfi_ranges("") == []
    assert rfi.eval_rfi_ranges("garbage") == []


def test_average_method_zap_and_normalize():
    n = 1 << 12
    rng = np.random.default_rng(1)
    spec = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(
        np.complex64)
    spec[100] = 1000.0 + 0j  # strong RFI line
    threshold = 10.0
    coeff = rfi.normalization_coefficient(n, 256)

    got = np.asarray(rfi.mitigate_rfi_average_and_normalize(
        jnp.asarray(spec), threshold, coeff))

    power = np.abs(spec) ** 2
    mean_power = power.mean(dtype=np.float64)
    zap = power > threshold * mean_power
    assert zap[100]
    assert got[100] == 0
    np.testing.assert_allclose(got[~zap], spec[~zap] * np.float32(coeff),
                               rtol=1e-5)


def test_normalization_coefficient():
    # (N^2 / nchan)^-0.5 (ref: rfi_mitigation_pipe.hpp:61-65)
    n, nchan = 1 << 20, 1 << 15
    expected = (float(n) * float(n) / nchan) ** -0.5
    # the reference evaluates this in float (rfi_mitigation_pipe.hpp:61-65)
    assert abs(rfi.normalization_coefficient(n, nchan) / expected - 1) < 1e-6


def test_manual_zap_inverted_band():
    """J1644-4559 style: freq_low 1437, bandwidth -64, zap 1418-1422 MHz
    (ref: srtb_config_1644-4559.cfg + rfi_mitigation.hpp:102-143)."""
    n = 64
    f_low, bw = 1437.0, -64.0
    mask = rfi.rfi_ranges_to_mask([(1418.0, 1422.0)], n, f_low, bw)
    assert mask is not None
    lo = round((1422.0 - f_low) / bw * (n - 1))
    hi = round((1418.0 - f_low) / bw * (n - 1))
    expected = np.zeros(n, dtype=bool)
    expected[lo:hi + 1] = True
    np.testing.assert_array_equal(mask, expected)

    spec = jnp.ones(n, dtype=jnp.complex64)
    got = np.asarray(rfi.mitigate_rfi_manual(spec, jnp.asarray(mask)))
    np.testing.assert_array_equal(got == 0, expected)


def test_manual_zap_out_of_range_warns_not_zaps():
    mask = rfi.rfi_ranges_to_mask([(10.0, 20.0)], 64, 1437.0, -64.0)
    assert mask is None


def test_spectral_kurtosis():
    """Gaussian noise rows survive; a CW tone row (SK -> 1... actually
    constant-amplitude -> SK near 1, zapped low) and an impulsive row
    (SK high) are zapped."""
    rng = np.random.default_rng(5)
    m = 512  # time samples
    nfreq = 8
    wf = (rng.standard_normal((nfreq, m))
          + 1j * rng.standard_normal((nfreq, m))).astype(np.complex64)
    wf[2] = 1.0 + 0j              # constant amplitude: SK ~ 1 < low threshold
    wf[5, :] = 0.01
    wf[5, 100] = 300.0            # impulsive: SK >> high threshold
    thr = 1.1

    got = np.asarray(rfi.mitigate_rfi_spectral_kurtosis(jnp.asarray(wf), thr))

    # numpy oracle (ref: rfi_mitigation.hpp:290-341)
    x2 = np.abs(wf.astype(np.complex128)) ** 2
    s2 = x2.sum(axis=1)
    s4 = (x2 * x2).sum(axis=1)
    sk = m * s4 / (s2 * s2)
    scale = (m - 1.0) / (m + 1.0)
    hi = max(thr, 2 - thr) * scale + 1
    lo = min(thr, 2 - thr) * scale + 1
    zap = (sk > hi) | (sk < lo)
    assert zap[2] and zap[5]
    assert not zap[0]
    for i in range(nfreq):
        if zap[i]:
            assert np.all(got[i] == 0)
        else:
            np.testing.assert_array_equal(got[i], wf[i])
