"""Multi-host (DCN-analog) tests.

The reference has no distributed layer to test; this validates the one
the TPU build adds.  Strategy (SURVEY.md §4 implication): a real
two-process ``jax.distributed`` group on CPU — cross-process Gloo
collectives standing in for DCN, intra-process virtual devices standing
in for ICI — plus single-process checks of the hybrid mesh layout.
"""

import os
import subprocess
import sys
import textwrap

import jax
import numpy as np
import pytest

from srtb_tpu.parallel import distributed as D


def test_hybrid_mesh_single_slice_layout():
    # 8 virtual CPU devices, no slice_index -> one slice; n_seq=2 must
    # give a 4x2 ("dm","seq") mesh with seq-contiguous rows
    mesh = D.hybrid_dm_seq_mesh(n_seq=2)
    assert mesh.axis_names == ("dm", "seq")
    assert mesh.devices.shape == (4, 2)
    flat = [d.id for d in mesh.devices.reshape(-1)]
    assert flat == sorted(flat)  # contiguous blocks per dm row


def test_hybrid_mesh_multi_slice_dm_across_dcn():
    # fake two slices by wrapping devices; dm rows must never mix slices
    class FakeDev:
        def __init__(self, d, s):
            self._d, self.slice_index, self.id = d, s, d.id

    devs = jax.devices()
    fake = [FakeDev(d, s) for s, half in
            enumerate((devs[:4], devs[4:])) for d in half]
    mesh_devices = D.hybrid_dm_seq_mesh(n_seq=2, devices=fake).devices
    assert mesh_devices.shape == (4, 2)
    for row in mesh_devices:
        assert len({d.slice_index for d in row}) == 1  # seq stays on ICI
    # dm axis spans both slices
    assert {row[0].slice_index for row in mesh_devices} == {0, 1}


def test_hybrid_mesh_rejects_bad_seq():
    with pytest.raises(ValueError):
        D.hybrid_dm_seq_mesh(n_seq=3)  # 3 does not divide 8


_WORKER = textwrap.dedent("""
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    from srtb_tpu.parallel import distributed as D
    D.initialize(f"127.0.0.1:{port}", nproc, pid)
    assert jax.process_count() == nproc
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = D.hybrid_dm_seq_mesh(n_seq=2)   # 2 procs x 2 devs -> dm=2,seq=2
    assert mesh.devices.shape == (2, 2)
    # seq rows must stay within one process (the "slice"/ICI domain)
    for row in mesh.devices:
        assert len({d.process_index for d in row}) == 1

    # cross-process collective over the full mesh: global psum of a
    # (dm, seq)-sharded array
    f = jax.jit(jax.shard_map(
        lambda x: jax.lax.psum(jax.lax.psum(x, "seq"), "dm"),
        mesh=mesh, in_specs=P("dm", "seq"), out_specs=P()))
    n_dm, n_seq = mesh.devices.shape
    global_shape = (n_dm * 2, n_seq * 3)
    sharding = NamedSharding(mesh, P("dm", "seq"))

    def shard_value(index):
        # value = global row-major index, so the expected sum is exact
        full = np.arange(np.prod(global_shape), dtype=np.float32)
        return full.reshape(global_shape)[index]

    arr = jax.make_array_from_callback(global_shape, sharding, shard_value)
    out = np.asarray(jax.device_get(f(arr)))
    expected = np.arange(np.prod(global_shape), dtype=np.float32).sum()
    assert out.reshape(-1).sum() == expected, (out, expected)

    local = D.process_local_dm_indices(mesh, n_trials=4)
    assert local == [pid, pid + 2], local

    # the sequence-parallel four-step FFT across the process (DCN)
    # boundary: 4-device seq mesh spanning both processes
    from srtb_tpu.parallel import mesh as M
    from srtb_tpu.parallel.dist_fft import dist_fft
    seq_mesh = M.seq_mesh(4)
    n = 1 << 10
    rng = np.random.default_rng(7)
    host_x = (rng.normal(size=n) + 1j * rng.normal(size=n)
              ).astype(np.complex64)
    seq_sharding = NamedSharding(seq_mesh, P("seq"))
    x = jax.make_array_from_callback(
        (n,), seq_sharding, lambda idx: host_x[idx])
    y = dist_fft(x, seq_mesh)
    expected = np.fft.fft(host_x).astype(np.complex64)
    for shard in y.addressable_shards:
        got = np.asarray(shard.data)
        want = expected[shard.index]
        assert np.allclose(got, want, rtol=2e-3, atol=2e-2 * n ** 0.5), \
            np.abs(got - want).max()
    print(f"WORKER_OK pid={pid}", flush=True)
""")


def test_two_process_group_collectives(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    # keep the axon sitecustomize (which dials a TPU relay at import) out
    # of the subprocesses; they must be plain CPU jax
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    port = 12000 + (os.getpid() % 1000)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(pid), "2", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"WORKER_OK pid={pid}" in out
