"""Multi-host (DCN-analog) tests.

The reference has no distributed layer to test; this validates the one
the TPU build adds.  Strategy (SURVEY.md §4 implication): a real
two-process ``jax.distributed`` group on CPU — cross-process Gloo
collectives standing in for DCN, intra-process virtual devices standing
in for ICI — plus single-process checks of the hybrid mesh layout.
"""

import os
import subprocess
import sys
import textwrap

import jax
import numpy as np
import pytest

from srtb_tpu.parallel import distributed as D


def test_hybrid_mesh_single_slice_layout():
    # 8 virtual CPU devices, no slice_index -> one slice; n_seq=2 must
    # give a 4x2 ("dm","seq") mesh with seq-contiguous rows
    mesh = D.hybrid_dm_seq_mesh(n_seq=2)
    assert mesh.axis_names == ("dm", "seq")
    assert mesh.devices.shape == (4, 2)
    flat = [d.id for d in mesh.devices.reshape(-1)]
    assert flat == sorted(flat)  # contiguous blocks per dm row


def test_hybrid_mesh_multi_slice_dm_across_dcn():
    # fake two slices by wrapping devices; dm rows must never mix slices
    class FakeDev:
        def __init__(self, d, s):
            self._d, self.slice_index, self.id = d, s, d.id

    devs = jax.devices()
    fake = [FakeDev(d, s) for s, half in
            enumerate((devs[:4], devs[4:])) for d in half]
    mesh_devices = D.hybrid_dm_seq_mesh(n_seq=2, devices=fake).devices
    assert mesh_devices.shape == (4, 2)
    for row in mesh_devices:
        assert len({d.slice_index for d in row}) == 1  # seq stays on ICI
    # dm axis spans both slices
    assert {row[0].slice_index for row in mesh_devices} == {0, 1}


def test_hybrid_mesh_rejects_bad_seq():
    with pytest.raises(ValueError):
        D.hybrid_dm_seq_mesh(n_seq=3)  # 3 does not divide 8


_WORKER = textwrap.dedent("""
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    from srtb_tpu.parallel import distributed as D
    D.initialize(f"127.0.0.1:{port}", nproc, pid)
    assert jax.process_count() == nproc
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = D.hybrid_dm_seq_mesh(n_seq=2)   # 2 procs x 2 devs -> dm=2,seq=2
    assert mesh.devices.shape == (2, 2)
    # seq rows must stay within one process (the "slice"/ICI domain)
    for row in mesh.devices:
        assert len({d.process_index for d in row}) == 1

    # cross-process collective over the full mesh: global psum of a
    # (dm, seq)-sharded array
    from srtb_tpu.parallel.compat import shard_map
    f = jax.jit(shard_map(
        lambda x: jax.lax.psum(jax.lax.psum(x, "seq"), "dm"),
        mesh=mesh, in_specs=P("dm", "seq"), out_specs=P()))
    n_dm, n_seq = mesh.devices.shape
    global_shape = (n_dm * 2, n_seq * 3)
    sharding = NamedSharding(mesh, P("dm", "seq"))

    def shard_value(index):
        # value = global row-major index, so the expected sum is exact
        full = np.arange(np.prod(global_shape), dtype=np.float32)
        return full.reshape(global_shape)[index]

    arr = jax.make_array_from_callback(global_shape, sharding, shard_value)
    out = np.asarray(jax.device_get(f(arr)))
    expected = np.arange(np.prod(global_shape), dtype=np.float32).sum()
    assert out.reshape(-1).sum() == expected, (out, expected)

    # contiguous-block trial ownership, matching P("dm") sharding: with
    # dm=2 rows and 4 trials, row pid owns trials [2*pid, 2*pid+1]
    local = D.process_local_dm_indices(mesh, n_trials=4)
    assert local == [2 * pid, 2 * pid + 1], local

    # full multi-host segment step: DM trials across the process (DCN)
    # boundary, sequence sharding within each process (ICI)
    from srtb_tpu.config import Config
    from srtb_tpu.parallel.segment_dist import DistSegmentProcessor
    cfg = Config(
        baseband_input_count=1 << 14, baseband_input_bits=8,
        baseband_format_type="simple", baseband_freq_low=1405.0,
        baseband_bandwidth=64.0, baseband_sample_rate=128e6, dm=30.0,
        spectrum_channel_count=1 << 6, signal_detect_max_boxcar_length=32,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        baseband_reserve_sample=False)
    proc = DistSegmentProcessor(cfg, mesh, dm_list=[0.0, 15.0, 30.0, 60.0])
    raw = np.random.default_rng(3).integers(
        0, 256, size=cfg.segment_bytes(1), dtype=np.uint8)
    res = proc.process(raw)
    peaks = np.asarray(res.snr_peaks)     # replicated -> readable anywhere
    counts = np.asarray(res.signal_counts)
    assert peaks.shape[0] == 4 and np.isfinite(peaks).all()
    import hashlib
    digest = hashlib.sha256(
        peaks.tobytes() + counts.tobytes()).hexdigest()[:16]
    print(f"WORKER_DIGEST {digest}", flush=True)

    # the sequence-parallel four-step FFT across the process (DCN)
    # boundary: 4-device seq mesh spanning both processes
    from srtb_tpu.parallel import mesh as M
    from srtb_tpu.parallel.dist_fft import dist_fft
    seq_mesh = M.seq_mesh(4)
    n = 1 << 10
    rng = np.random.default_rng(7)
    host_x = (rng.normal(size=n) + 1j * rng.normal(size=n)
              ).astype(np.complex64)
    seq_sharding = NamedSharding(seq_mesh, P("seq"))
    x = jax.make_array_from_callback(
        (n,), seq_sharding, lambda idx: host_x[idx])
    y = dist_fft(x, seq_mesh)
    expected = np.fft.fft(host_x).astype(np.complex64)
    for shard in y.addressable_shards:
        got = np.asarray(shard.data)
        want = expected[shard.index]
        assert np.allclose(got, want, rtol=2e-3, atol=2e-2 * n ** 0.5), \
            np.abs(got - want).max()
    print(f"WORKER_OK pid={pid}", flush=True)
""")


def test_two_process_group_collectives(tmp_path):
    import jax
    if jax.__version_info__ < (0, 5):
        # jaxlib 0.4.x's CPU client rejects cross-process computations
        # outright ("Multiprocess computations aren't implemented on
        # the CPU backend"); the gloo-backed CPU collectives this test
        # exercises exist only on newer runtimes
        pytest.skip("cross-process CPU collectives unsupported by this "
                    "jaxlib")
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    # keep the axon sitecustomize (which dials a TPU relay at import) out
    # of the subprocesses; they must be plain CPU jax
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    import socket
    with socket.socket() as s:  # let the OS pick a free port
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(pid), "2", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"WORKER_OK pid={pid}" in out
    # the replicated trial summaries must be identical on every host
    digests = {line.split()[1] for out in outs for line in out.splitlines()
               if line.startswith("WORKER_DIGEST")}
    assert len(digests) == 1, digests
