"""Device pool + placement policy units (pipeline/pool.py,
pipeline/placement.py).

The elastic-fleet control plane must be provable WITHOUT a fleet:
the pool's deterministic virtual halt (the CPU-CI stand-in for a
dying accelerator), the per-member plan-cache/halt-domain isolation,
the health-state gauge twins, and the pure placement policy
(least-loaded, soft same-tenant anti-affinity, pin validation) are
all unit-scoped here; tests/test_fleet.py proves the same machinery
end-to-end through live migration.
"""

import pytest

from srtb_tpu.pipeline import placement
from srtb_tpu.pipeline.pool import (STATE_DRAINING, STATE_HALTED,
                                    STATE_OK, DevicePool, PoolDevice)
from srtb_tpu.resilience.errors import DeviceHalt
from srtb_tpu.utils.metrics import metrics


@pytest.fixture(autouse=True)
def _fresh_metrics():
    metrics.reset()
    yield
    metrics.reset()


# ------------------------------------------------------------- pool


def test_pool_single_member_by_default():
    class Cfg:
        fleet_devices = 0

    pool = DevicePool.from_config(Cfg())
    assert len(pool) == 1
    assert pool.devices[0].label == "dev0"
    assert pool.healthy() == pool.devices
    assert metrics.get("fleet_pool_size") == 1


def test_pool_virtual_members_have_distinct_caches():
    class Cfg:
        fleet_devices = 3

    pool = DevicePool.from_config(Cfg())
    assert len(pool) == 3
    caches = {id(d.plans) for d in pool}
    assert len(caches) == 3  # per-member halt domains, never shared
    assert [d.label for d in pool] == ["dev0", "dev1", "dev2"]
    assert metrics.get("fleet_pool_size") == 3


def test_scheduled_halt_fires_exactly_once():
    dev = PoolDevice(0)
    dev.schedule_halt(after_dispatches=2)
    dev.note_dispatch()
    dev.note_dispatch()  # == threshold: still healthy
    with pytest.raises(DeviceHalt, match="dev0"):
        dev.note_dispatch()  # first dispatch PAST the threshold
    # one-shot: the member is being drained, not flapping
    dev.note_dispatch()
    assert dev.dispatches == 4


def test_scheduled_halt_skips_formed_batches():
    """check=False (the formed-batch dispatch clock) counts but never
    fires: scheduled halts land at solo dispatch boundaries where the
    lane's healer classifies them."""
    dev = PoolDevice(0)
    dev.schedule_halt(after_dispatches=0)
    dev.note_dispatch(check=False)
    dev.note_dispatch(check=False)
    assert dev.dispatches == 2
    with pytest.raises(DeviceHalt):
        dev.note_dispatch()


def test_state_gauge_publishes_per_device():
    pool = DevicePool(2)
    pool.devices[1].set_state(STATE_DRAINING)
    states = metrics.by_label("fleet_device_state", label="device")
    assert states == {"dev0": 0.0, "dev1": 1.0}
    pool.devices[1].set_state(STATE_HALTED)
    assert metrics.by_label("fleet_device_state",
                            label="device")["dev1"] == 2.0
    assert pool.healthy() == [pool.devices[0]]


def test_invalidate_all_rearms_halted_members():
    pool = DevicePool(2)
    pool.devices[0].set_state(STATE_HALTED)
    pool.invalidate_all()
    assert all(d.state == STATE_OK for d in pool)
    assert len(pool.healthy()) == 2


def test_pool_counts_sum_members():
    pool = DevicePool(2)
    pool.devices[0].plans.compiles = 1
    pool.devices[1].plans.compiles = 1
    pool.devices[1].plans.hits = 3
    assert pool.compiles == 2 and pool.hits == 3
    pool.devices[0].note_dispatch()
    pool.devices[1].note_dispatch()
    assert pool.total_dispatches == 2


# -------------------------------------------------------- placement


class _Spec:
    def __init__(self, name, pin_device=None):
        self.name = name
        self.pin_device = pin_device


def test_tenant_is_prefix_before_dot():
    assert placement.tenant_of("radioA.band0") == "radioA"
    assert placement.tenant_of("flat") == "flat"


def test_initial_placement_least_loaded_min_index_tie():
    devs = DevicePool(3).devices
    assert placement.choose_initial(
        _Spec("s"), devs, {0: 2, 1: 1, 2: 1}).index == 1
    # full tie -> deterministic min index
    assert placement.choose_initial(
        _Spec("s"), devs, {}).index == 0


def test_anti_affinity_prefers_tenant_clean_member():
    devs = DevicePool(2).devices
    # equal load, but dev0 already hosts the tenant: dev1 wins
    got = placement.choose_initial(
        _Spec("radioA.band1"), devs, {0: 1, 1: 1},
        tenants_by_device={0: {"radioA"}, 1: {"radioB"}})
    assert got.index == 1
    # anti-affinity is SOFT: a strictly less-loaded co-tenant device
    # still wins over an empty-of-tenant but busier one
    got = placement.choose_initial(
        _Spec("radioA.band2"), devs, {0: 0, 1: 5},
        tenants_by_device={0: {"radioA"}})
    assert got.index == 0


def test_pin_device_validated_pure_config():
    devs = DevicePool(2).devices
    assert placement.choose_initial(
        _Spec("s", pin_device=1), devs, {}).index == 1
    with pytest.raises(ValueError, match="pin_device=7"):
        placement.choose_initial(_Spec("s", pin_device=7), devs, {})
    # a pin onto an unhealthy (pre-filtered) member fails the same way
    with pytest.raises(ValueError, match="pin_device=0"):
        placement.choose_initial(_Spec("s", pin_device=0),
                                 devs[1:], {})


def test_choose_target_excludes_current_and_handles_no_peer():
    devs = DevicePool(2).devices
    got = placement.choose_target("s", 0, devs, {0: 1, 1: 9})
    assert got.index == 1  # only peer, load notwithstanding
    assert placement.choose_target("s", 0, devs[:1], {}) is None
