"""Fault-tolerant streaming supervisor tests (srtb_tpu/resilience/).

Covers the acceptance criteria of the resilience subsystem:
- a transient fault injected at each of the six named sites (ingest,
  h2d, dispatch, fetch, sink_write, checkpoint) retries to success
  with detect output bit-identical to a fault-free run and
  ``segments_dropped == 0``;
- fatal faults escalate to a clean, loud shutdown;
- the segment watchdog cancels and re-dispatches a wedged in-flight
  segment (fetch never ready) with bit-identical output, and
  escalates when the requeue budget is exhausted;
- the supervisor restarts a crashed sink pipe with bounded budget and
  no lost segments, and escalates past the budget;
- degradation steps (shed waterfall dumps, shed baseband dumps) are
  accounted — no silent loss;
- restart-after-crash resumes from the checkpoint and completes the
  remainder bit-identically;
- file outputs are crash-consistent (temp + atomic rename, orphan
  sweep at startup) and shutdown joins are bounded with a wedged-
  thread report.
"""

import json
import os
import threading
import time
from typing import NamedTuple

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.pipeline.runtime import Pipeline
from srtb_tpu.pipeline.work import SegmentWork
from srtb_tpu.resilience import errors as E
from srtb_tpu.resilience.degrade import DegradationLadder
from srtb_tpu.resilience.faults import (FaultInjector, InjectedFatal,
                                        parse_plan)
from srtb_tpu.resilience.retry import RetryPolicy, retry_call
from srtb_tpu.resilience.supervisor import Supervisor
from srtb_tpu.utils.metrics import metrics

SITES = ("ingest", "h2d", "dispatch", "fetch", "sink_write",
         "checkpoint")


# ------------------------------------------------------------ taxonomy


def test_classify_taxonomy():
    assert E.classify(E.TransientError("x")) == E.TRANSIENT
    assert E.classify(E.DataLossError("x")) == E.DATA_LOSS
    assert E.classify(E.FatalError("x")) == E.FATAL
    # stdlib momentary conditions are transient
    assert E.classify(TimeoutError()) == E.TRANSIENT
    assert E.classify(InterruptedError()) == E.TRANSIENT
    assert E.classify(ConnectionResetError()) == E.TRANSIENT
    import errno
    assert E.classify(OSError(errno.EAGAIN, "x")) == E.TRANSIENT
    # unknown failures stay fatal: retrying unclassified errors hides bugs
    assert E.classify(RuntimeError("bug")) == E.FATAL
    assert E.classify(ValueError("bug")) == E.FATAL
    assert E.classify(OSError(errno.ENOENT, "x")) == E.FATAL


# --------------------------------------------------------------- retry


def test_retry_policy_backoff_deterministic():
    p = RetryPolicy(max_attempts=5, backoff_base_s=0.1,
                    backoff_max_s=0.5, jitter=0.25)
    seq = [p.backoff("ingest", a) for a in range(1, 5)]
    # deterministic: same site+attempt, same delay
    assert seq == [p.backoff("ingest", a) for a in range(1, 5)]
    # exponential-with-jitter, bounded by max*(1+jitter)
    assert all(d <= 0.5 * 1.25 for d in seq)
    assert seq[1] > seq[0] * 1.2  # grows despite jitter
    # different sites jitter differently
    assert p.backoff("fetch", 1) != p.backoff("ingest", 1)


def test_retry_call_transient_then_success():
    metrics.reset()
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise E.TransientError("hiccup")
        return "ok"

    p = RetryPolicy(max_attempts=3, backoff_base_s=0.001)
    assert retry_call(flaky, p, "t", sleep=lambda s: None) == "ok"
    assert len(calls) == 3
    assert metrics.get("retries_total") == 2
    assert metrics.get("retries_t") == 2
    metrics.reset()


def test_retry_call_fatal_immediate_and_budget_exhausted():
    p = RetryPolicy(max_attempts=3, backoff_base_s=0.001)
    calls = []

    def fatal():
        calls.append(1)
        raise RuntimeError("bug")

    with pytest.raises(RuntimeError):
        retry_call(fatal, p, "t", sleep=lambda s: None)
    assert len(calls) == 1  # fatal: no retry

    calls.clear()

    def always():
        calls.append(1)
        raise E.TransientError("down")

    with pytest.raises(E.TransientError):
        retry_call(always, p, "t", sleep=lambda s: None)
    assert len(calls) == 3  # budget spent


def test_retry_call_data_loss_is_accounted():
    metrics.reset()
    calls = []

    def torn():
        calls.append(1)
        if len(calls) < 2:
            raise E.DataLossError("torn block")
        return "ok"

    p = RetryPolicy(max_attempts=3, backoff_base_s=0.001)
    assert retry_call(torn, p, "t", sleep=lambda s: None) == "ok"
    # the retry succeeded but the loss event itself was counted
    assert metrics.get("data_loss_total") == 1
    metrics.reset()


def test_retry_deadline_bounds_total_time():
    p = RetryPolicy(max_attempts=50, backoff_base_s=0.05,
                    deadline_s=0.01)

    def always():
        raise E.TransientError("down")

    t0 = time.monotonic()
    with pytest.raises(E.TransientError):
        retry_call(always, p, "t")
    assert time.monotonic() - t0 < 1.0  # gave up at the deadline


# ---------------------------------------------------------- fault plan


def test_fault_plan_parse_roundtrip():
    specs = parse_plan("ingest:raise@1, fetch:stall=0.25@2,"
                       "sink_write:corrupt@3,dispatch:fatal@0")
    assert [str(s) for s in specs] == [
        "ingest:raise@1", "fetch:stall=0.25@2",
        "sink_write:corrupt@3", "dispatch:fatal@0"]
    inj = FaultInjector.from_plan("")
    assert inj is None  # zero-cost off
    inj = FaultInjector.from_plan("ingest:raise@1")
    assert inj.armed("ingest") and not inj.armed("fetch")
    inj.fire("ingest", 0)  # wrong index: nothing
    with pytest.raises(E.TransientError):
        inj.fire("ingest", 1)
    inj.fire("ingest", 1)  # fires once only
    assert inj.unfired() == []


@pytest.mark.parametrize("bad", [
    "nosuchsite:raise@1", "ingest:explode@1", "ingest:raise",
    "ingest:stall@1", "ingest:stall=-1@1", "ingest:raise@x"])
def test_fault_plan_parse_rejects(bad):
    with pytest.raises(ValueError):
        parse_plan(bad)


def test_fault_plan_rejects_duplicate_site_index():
    """Two entries at the same (site, index) would silently shadow one
    another; the fail-at-startup contract must catch the typo."""
    with pytest.raises(ValueError, match="duplicate"):
        FaultInjector.from_plan("ingest:raise@1,ingest:fatal@1")


# ------------------------------------------------------------- ladder


def test_degradation_ladder_steps_and_recovers():
    metrics.reset()
    lad = DegradationLadder(high=0.8, low=0.2, hold=2)
    assert lad.observe(0.5, False) == 0     # mid-band: hold
    assert lad.observe(0.9, False) == 0     # 1st above
    assert lad.observe(0.9, False) == 1     # hold reached: step up
    assert lad.observe(0.9, False) == 1
    assert lad.observe(0.9, False) == 2     # again
    # loss alone is pressure even with an empty queue
    assert lad.observe(0.0, True) == 2
    assert lad.observe(0.0, True) == 3
    assert lad.observe(0.0, True) == 3      # top rung is sticky
    # recovery needs `hold` consecutive clear observations
    assert lad.observe(0.1, False) == 3
    assert lad.observe(0.1, False) == 2
    assert metrics.get("degrade_level") == 2
    assert metrics.get("degrade_steps") == 3
    assert metrics.get("degrade_recoveries") == 1
    metrics.reset()


def test_degradation_ladder_validates():
    with pytest.raises(ValueError):
        DegradationLadder(high=0.2, low=0.5)


# ---------------------------------------------------------- supervisor


def test_supervisor_budget_and_escalation():
    metrics.reset()
    t = [0.0]
    sup = Supervisor("w", max_restarts=2, window_s=10.0,
                     clock=lambda: t[0])
    exc = E.TransientError("crash")
    assert sup.should_restart(exc)
    assert sup.should_restart(exc)
    assert not sup.should_restart(exc)  # budget spent
    t[0] = 20.0  # window slides: budget recovers
    assert sup.should_restart(exc)
    assert metrics.get("worker_restarts") == 3
    assert metrics.get("worker_restarts_w") == 3
    # fatal crashes never restart (unless restart_fatal)
    assert not sup.should_restart(RuntimeError("bug"))
    assert Supervisor("g", restart_fatal=True).should_restart(
        RuntimeError("bug"))
    metrics.reset()


# ===================================================== pipeline fixtures


@pytest.fixture(scope="module")
def synth_file(tmp_path_factory):
    from srtb_tpu.io.synth import make_dispersed_baseband

    tmp = tmp_path_factory.mktemp("resilience")
    n = 1 << 14
    data = make_dispersed_baseband(n * 4, 1405.0, 64.0, 0.0,
                                   pulse_positions=n // 2, nbits=8)
    path = str(tmp / "bb.bin")
    data.tofile(path)
    return path, n


def _cfg(path, n, tmp_path, tag, **extra):
    return Config(
        baseband_input_count=n,
        baseband_input_bits=8,
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        input_file_path=path,
        baseband_output_file_prefix=str(tmp_path / f"{tag}_"),
        spectrum_channel_count=1 << 8,
        signal_detect_max_boxcar_length=64,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        baseband_reserve_sample=False,
        writer_thread_count=0,
        retry_backoff_base_s=0.001,
        **extra)


@pytest.fixture(scope="module")
def shared_processor(synth_file):
    """One compiled segment plan shared across pipelines (the fault
    knobs are not trace-relevant, so every run uses the same jits)."""
    from srtb_tpu.pipeline.segment import SegmentProcessor

    path, n = synth_file
    cfg = Config(baseband_input_count=n, baseband_input_bits=8,
                 baseband_freq_low=1405.0, baseband_bandwidth=64.0,
                 baseband_sample_rate=128e6,
                 spectrum_channel_count=1 << 8,
                 signal_detect_max_boxcar_length=64,
                 mitigate_rfi_average_method_threshold=100.0,
                 mitigate_rfi_spectral_kurtosis_threshold=2.0,
                 baseband_reserve_sample=False)
    return SegmentProcessor(cfg)


class _CaptureSink:
    def __init__(self):
        self.detects = []
        self.positives = []

    def push(self, work, positive):
        det = work.detect
        self.detects.append((
            np.asarray(det.signal_counts).copy(),
            np.asarray(det.zero_count).copy(),
            np.asarray(det.time_series).copy()))
        self.positives.append(bool(positive))


def _run_real(cfg, processor, sink=None):
    sinks = [sink] if sink is not None else []
    with Pipeline(cfg, sinks=sinks, processor=processor) as pipe:
        stats = pipe.run()
    return stats


def _assert_same_detects(a: _CaptureSink, b: _CaptureSink):
    assert len(a.detects) == len(b.detects)
    for (sc_a, zc_a, ts_a), (sc_b, zc_b, ts_b) in zip(a.detects,
                                                      b.detects):
        np.testing.assert_array_equal(sc_a, sc_b)
        np.testing.assert_array_equal(zc_a, zc_b)
        np.testing.assert_array_equal(ts_a, ts_b)
    assert a.positives == b.positives


@pytest.fixture(scope="module")
def fault_free_baseline(synth_file, shared_processor,
                        tmp_path_factory):
    """Detect outputs of a run with no faults — the bit-identity
    reference every recovery test compares against."""
    path, n = synth_file
    tmp = tmp_path_factory.mktemp("baseline")
    metrics.reset()
    sink = _CaptureSink()
    stats = _run_real(_cfg(path, n, tmp, "base", inflight_segments=2),
                      shared_processor, sink)
    metrics.reset()
    assert stats.segments == 4
    return stats, sink


# --------------------------------------- transient faults at every site


@pytest.mark.parametrize("site", SITES)
def test_transient_fault_retries_to_success(site, synth_file,
                                            shared_processor, tmp_path,
                                            fault_free_baseline):
    """One injected transient fault at each named site: the pipeline
    must complete with detect output bit-identical to the fault-free
    run, zero dropped segments, and the retry accounted."""
    path, n = synth_file
    base_stats, base_sink = fault_free_baseline
    metrics.reset()
    sink = _CaptureSink()
    extra = {}
    if site == "checkpoint":
        extra["checkpoint_path"] = str(tmp_path / f"{site}.json")
    cfg = _cfg(path, n, tmp_path, site, inflight_segments=2,
               fault_plan=f"{site}:raise@1", **extra)
    pipe = Pipeline(cfg, sinks=[sink], processor=shared_processor)
    with pipe:
        stats = pipe.run()
    assert stats.segments == base_stats.segments
    _assert_same_detects(base_sink, sink)
    assert pipe.faults.unfired() == [], "fault never fired"
    assert metrics.get("retries_total") == 1
    assert metrics.get(f"retries_{site}") == 1
    assert metrics.get("segments_dropped") == 0
    metrics.reset()


def test_all_six_sites_one_run_acceptance(synth_file, shared_processor,
                                          tmp_path,
                                          fault_free_baseline):
    """The acceptance case: one transient fault at each of the six
    sites in a SINGLE run — bit-identical output, segments_dropped ==
    0, and every recovery counter visible in the Prometheus exposition
    and the v3 journal."""
    from srtb_tpu.tools import telemetry_report as TR

    path, n = synth_file
    base_stats, base_sink = fault_free_baseline
    metrics.reset()
    sink = _CaptureSink()
    plan = ("ingest:raise@1,h2d:raise@1,dispatch:raise@2,"
            "fetch:raise@2,sink_write:raise@3,checkpoint:raise@3")
    cfg = _cfg(path, n, tmp_path, "all6", inflight_segments=2,
               fault_plan=plan,
               checkpoint_path=str(tmp_path / "all6.json"),
               telemetry_journal_path=str(tmp_path / "all6.jsonl"))
    pipe = Pipeline(cfg, sinks=[sink], processor=shared_processor)
    with pipe:
        stats = pipe.run()
    assert stats.segments == base_stats.segments
    _assert_same_detects(base_sink, sink)
    assert pipe.faults.unfired() == []
    assert metrics.get("retries_total") == 6
    assert metrics.get("segments_dropped") == 0
    # counters visible in /metrics (Prometheus text exposition)
    prom = metrics.prometheus()
    assert "srtb_retries_total 6" in prom
    assert "srtb_faults_injected 6" in prom
    assert "srtb_degrade_level" in prom
    # ... and in the journal (schema v4 since the self-healing PR)
    recs = TR.load(cfg.telemetry_journal_path)
    assert len(recs) == stats.segments
    for r in recs:
        assert r["v"] == 11
        for key in ("degrade_level", "retries", "requeues", "restarts",
                    "shed_waterfalls", "shed_baseband"):
            assert key in r, (key, r)
    # the checkpoint-site retry of the LAST segment lands after that
    # segment's journal write, so the final record carries 5 of the 6
    assert recs[-1]["retries"] == 5
    assert recs[-1]["segments_dropped"] == 0
    rep = TR.report(cfg.telemetry_journal_path)
    assert rep["resilience"]["retries"] == 5
    assert rep["resilience"]["degrade_level_max"] == 0
    metrics.reset()


def test_fatal_fault_escalates_cleanly(synth_file, shared_processor,
                                       tmp_path):
    """A fatal fault must not be retried: the run raises it, and the
    engine shuts down cleanly (no hang, close() fine)."""
    path, n = synth_file
    metrics.reset()
    cfg = _cfg(path, n, tmp_path, "fatal", inflight_segments=2,
               fault_plan="dispatch:fatal@1")
    pipe = Pipeline(cfg, sinks=[], processor=shared_processor)
    with pipe:
        with pytest.raises(InjectedFatal):
            pipe.run()
    assert metrics.get("retries_total") == 0
    metrics.reset()


def test_corrupt_fault_retried_and_accounted(synth_file,
                                             shared_processor,
                                             tmp_path,
                                             fault_free_baseline):
    """A data-loss fault retries to success like a transient, but the
    loss occurrence itself is counted."""
    path, n = synth_file
    base_stats, base_sink = fault_free_baseline
    metrics.reset()
    sink = _CaptureSink()
    cfg = _cfg(path, n, tmp_path, "corrupt", inflight_segments=2,
               fault_plan="ingest:corrupt@2")
    stats = _run_real(cfg, shared_processor, sink)
    assert stats.segments == base_stats.segments
    _assert_same_detects(base_sink, sink)
    assert metrics.get("data_loss_total") == 1
    assert metrics.get("retries_total") == 1
    metrics.reset()


# ----------------------------------------------------- watchdog requeue


class _StubDetect(NamedTuple):
    signal_counts: object
    zero_count: object
    time_series: object


class _NeverReady:
    """Device-array stand-in that never materializes (a wedged fetch)."""

    def is_ready(self) -> bool:
        return False

    def __array__(self, dtype=None, copy=None):
        raise AssertionError("a cancelled segment's results were read")


class _WedgeProcessor:
    """First ``wedge_times`` dispatches return never-ready results;
    later dispatches (including the watchdog's re-dispatch of the same
    segment) return deterministic host values derived from the input."""

    def __init__(self, wedge_times: int):
        self.wedge_times = wedge_times
        self.dispatches = 0

    def process(self, raw):
        self.dispatches += 1
        if self.dispatches <= self.wedge_times:
            det = _StubDetect(_NeverReady(), _NeverReady(),
                              _NeverReady())
            return None, det
        val = float(np.asarray(raw, dtype=np.float32).sum())
        det = _StubDetect(
            signal_counts=np.zeros((1, 4), np.int64),
            zero_count=np.asarray(0),
            time_series=np.asarray([val], np.float32))
        return None, det


class _CountingSource:
    def __init__(self, n_segments: int, seg_bytes: int = 64):
        self.n = n_segments
        self.seg_bytes = seg_bytes
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self) -> SegmentWork:
        if self._i >= self.n:
            raise StopIteration
        self._i += 1
        return SegmentWork(
            data=np.full(self.seg_bytes, self._i, np.uint8),
            timestamp=self._i)


def _watchdog_cfg(tmp_path, tag, **extra):
    return Config(baseband_input_count=64,
                  baseband_reserve_sample=False,
                  writer_thread_count=0,
                  retry_backoff_base_s=0.001,
                  telemetry_journal_path=str(tmp_path / f"{tag}.jsonl"),
                  **extra)


def test_watchdog_requeues_wedged_segment(tmp_path):
    """Segment 0's first dispatch never becomes ready: the watchdog
    must cancel it at the deadline, re-dispatch from the retained host
    buffer, and drain bit-identical output vs a run that never wedged
    — with the requeue accounted and nothing dropped."""
    from srtb_tpu.tools import telemetry_report as TR

    metrics.reset()
    outs = {}
    for tag, wedge in (("clean", 0), ("wedged", 1)):
        cfg = _watchdog_cfg(tmp_path, tag, inflight_segments=2,
                            segment_deadline_s=0.12,
                            segment_watchdog_requeues=2)
        sink = _CaptureSink()
        pipe = Pipeline(cfg, source=_CountingSource(4), sinks=[sink],
                        processor=_WedgeProcessor(wedge))
        with pipe:
            stats = pipe.run()
        outs[tag] = (stats, sink)
        assert stats.segments == 4
    _assert_same_detects(outs["clean"][1], outs["wedged"][1])
    assert metrics.get("watchdog_requeues") == 1
    assert metrics.get("segments_dropped") == 0
    recs = TR.load(str(tmp_path / "wedged.jsonl"))
    assert [r["segment"] for r in recs] == list(range(4))
    assert recs[-1]["requeues"] == 1
    metrics.reset()


def test_watchdog_escalates_after_requeue_budget(tmp_path):
    """A segment that stays wedged through every allowed requeue must
    escalate fatally (the device is gone), not loop forever."""
    metrics.reset()
    cfg = _watchdog_cfg(tmp_path, "esc", inflight_segments=2,
                        segment_deadline_s=0.08,
                        segment_watchdog_requeues=1)
    pipe = Pipeline(cfg, source=_CountingSource(3), sinks=[],
                    processor=_WedgeProcessor(10))
    with pipe:
        with pytest.raises(E.WatchdogEscalation):
            pipe.run()
    assert metrics.get("watchdog_requeues") == 1
    metrics.reset()


# ------------------------------------------------- supervisor restarts


class _InstantProcessor:
    def process(self, raw):
        val = float(np.asarray(raw, dtype=np.float32).sum())
        return None, _StubDetect(
            signal_counts=np.zeros((1, 4), np.int64),
            zero_count=np.asarray(0),
            time_series=np.asarray([val], np.float32))


class _CrashingSink:
    """Raises a transient-classified error on the first ``crashes``
    pushes, then records."""

    def __init__(self, crashes: int):
        self.left = crashes
        self.pushed = []

    def push(self, work, positive):
        if self.left > 0:
            self.left -= 1
            raise ConnectionResetError("sink backend lost")
        self.pushed.append(int(work.segment.timestamp))


def test_supervisor_restarts_crashed_sink_pipe(tmp_path):
    """Retry disabled, so the sink crash kills the pipe worker: the
    supervisor must restart it, replay the failed item (no segment
    lost, order kept), and account the restart."""
    from srtb_tpu.tools import telemetry_report as TR

    metrics.reset()
    n_seg = 6
    cfg = _watchdog_cfg(tmp_path, "restart", inflight_segments=3,
                        retry_max_attempts=1,  # crash reaches the pipe
                        supervisor_max_restarts=2)
    sink = _CrashingSink(crashes=1)
    pipe = Pipeline(cfg, source=_CountingSource(n_seg), sinks=[sink],
                    processor=_InstantProcessor())
    with pipe:
        stats = pipe.run()
    assert stats.segments == n_seg
    # every segment reached the sink exactly once, in order
    assert sink.pushed == list(range(1, n_seg + 1))
    assert metrics.get("worker_restarts") == 1
    assert metrics.get("worker_restarts_sink_drain") == 1
    recs = TR.load(str(tmp_path / "restart.jsonl"))
    assert [r["segment"] for r in recs] == list(range(n_seg))
    assert recs[-1]["restarts"] == 1
    metrics.reset()


def test_supervisor_replay_counts_signal_once(tmp_path):
    """A replayed drain re-runs the detection gate: a positive segment
    whose first attempt crashed in the sink stage (after the signal
    was already counted) must not inflate ``stats.signals``."""

    class _PositiveProcessor(_InstantProcessor):
        def process(self, raw):
            _, det = super().process(raw)
            return None, det._replace(
                signal_counts=np.ones((1, 4), np.int64))

    metrics.reset()
    n_seg = 4
    cfg = _watchdog_cfg(tmp_path, "replay_sig", inflight_segments=3,
                        retry_max_attempts=1,  # crash reaches the pipe
                        supervisor_max_restarts=2)
    sink = _CrashingSink(crashes=1)
    pipe = Pipeline(cfg, source=_CountingSource(n_seg), sinks=[sink],
                    processor=_PositiveProcessor())
    with pipe:
        stats = pipe.run()
    assert metrics.get("worker_restarts") == 1
    assert stats.segments == n_seg
    assert sink.pushed == list(range(1, n_seg + 1))
    # every segment is positive; the replayed one counts exactly once
    assert stats.signals == n_seg
    metrics.reset()


def test_sink_retry_is_exactly_once_per_sink(tmp_path):
    """A transient failure in one sink must not re-push the sinks that
    already succeeded: an in-place appender (WriteAllSink) would
    otherwise duplicate its stream bytes on every retry."""

    class _Appender:
        def __init__(self):
            self.got = []

        def push(self, work, positive):
            self.got.append(int(work.segment.timestamp))

    class _FlakySink:
        def __init__(self):
            self.fails = 1
            self.got = []

        def push(self, work, positive):
            if self.fails:
                self.fails -= 1
                raise ConnectionResetError("sink hiccup")
            self.got.append(int(work.segment.timestamp))

    metrics.reset()
    appender, flaky = _Appender(), _FlakySink()
    cfg = _watchdog_cfg(tmp_path, "once", inflight_segments=2)
    pipe = Pipeline(cfg, source=_CountingSource(3),
                    sinks=[appender, flaky],
                    processor=_InstantProcessor())
    with pipe:
        stats = pipe.run()
    assert stats.segments == 3
    assert metrics.get("retries_total") == 1
    # the appender saw every segment exactly once despite the retry
    assert appender.got == [1, 2, 3]
    assert flaky.got == [1, 2, 3]
    metrics.reset()


class _DrainCrashSink:
    """push always succeeds; drain() — reached via the checkpoint
    flush, i.e. AFTER the segment was accounted — crashes once."""

    def __init__(self, crashes: int = 1):
        self.left = crashes
        self.pushed = []

    def push(self, work, positive):
        self.pushed.append(int(work.segment.timestamp))

    def drain(self):
        if self.left > 0:
            self.left -= 1
            raise ConnectionResetError("flush lost")


def test_supervisor_skips_replay_after_accounting(tmp_path):
    """A crash landing AFTER the segment was accounted (here: in the
    checkpoint flush) must NOT be replayed — a replay would
    double-count the segment and shift every later journal index."""
    from srtb_tpu.tools import telemetry_report as TR

    metrics.reset()
    n_seg = 5
    cfg = _watchdog_cfg(tmp_path, "postacct", inflight_segments=3,
                        retry_max_attempts=1,
                        supervisor_max_restarts=2,
                        checkpoint_path=str(tmp_path / "pa.json"))
    sink = _DrainCrashSink(crashes=1)
    pipe = Pipeline(cfg, source=_CountingSource(n_seg), sinks=[sink],
                    processor=_InstantProcessor())
    with pipe:
        stats = pipe.run()
    assert stats.segments == n_seg
    assert metrics.get("worker_restarts") == 1
    # exactly-once accounting: no duplicate pushes, no duplicate or
    # shifted journal indices, checkpoint covers every segment
    assert sink.pushed == list(range(1, n_seg + 1))
    recs = TR.load(str(tmp_path / "postacct.jsonl"))
    assert [r["segment"] for r in recs] == list(range(n_seg))
    assert json.load(open(tmp_path / "pa.json"))["segments_done"] \
        == n_seg
    metrics.reset()


def test_supervisor_escalates_past_budget(tmp_path):
    """A sink that keeps crashing exhausts the restart budget and the
    original error escalates to the caller."""
    metrics.reset()
    cfg = _watchdog_cfg(tmp_path, "budget", inflight_segments=3,
                        retry_max_attempts=1,
                        supervisor_max_restarts=1)
    pipe = Pipeline(cfg, source=_CountingSource(8),
                    sinks=[_CrashingSink(crashes=100)],
                    processor=_InstantProcessor())
    with pipe:
        with pytest.raises(ConnectionResetError):
            pipe.run()
    assert metrics.get("worker_restarts") == 1
    metrics.reset()


def test_supervision_disabled_propagates_immediately(tmp_path):
    """supervisor_max_restarts = 0 restores the crash-propagation-only
    behavior."""
    metrics.reset()
    cfg = _watchdog_cfg(tmp_path, "nosup", inflight_segments=3,
                        retry_max_attempts=1,
                        supervisor_max_restarts=0)
    pipe = Pipeline(cfg, source=_CountingSource(4),
                    sinks=[_CrashingSink(crashes=1)],
                    processor=_InstantProcessor())
    with pipe:
        with pytest.raises(ConnectionResetError):
            pipe.run()
    assert metrics.get("worker_restarts") == 0
    metrics.reset()


# ----------------------------------------------------- degradation


class _WaterfallProcessor:
    def process(self, raw):
        det = _StubDetect(
            signal_counts=np.ones((1, 4), np.int64),  # always positive
            zero_count=np.asarray(0),
            time_series=np.zeros(4, np.float32))
        return np.zeros((2, 1, 4, 4), np.float32), det


class _SlowSheddableSink:
    sheddable = True

    def __init__(self, sink_s: float):
        self.sink_s = sink_s
        self.pushed = 0
        self.waterfalls = 0

    def push(self, work, positive):
        time.sleep(self.sink_s)
        self.pushed += 1
        if work.waterfall is not None:
            self.waterfalls += 1


def test_degradation_sheds_accounted(tmp_path):
    """Sustained sink backlog must walk the ladder: waterfall dumps
    shed first, then the sheddable sink skipped entirely — every shed
    counted, every segment still journaled (no silent loss)."""
    from srtb_tpu.tools import telemetry_report as TR

    metrics.reset()
    n_seg = 12
    cfg = _watchdog_cfg(tmp_path, "degrade", inflight_segments=2,
                        degrade_queue_high=0.4, degrade_queue_low=0.1,
                        degrade_hold_segments=2)
    sink = _SlowSheddableSink(0.02)
    pipe = Pipeline(cfg, source=_CountingSource(n_seg), sinks=[sink],
                    processor=_WaterfallProcessor())
    with pipe:
        stats = pipe.run()
    assert stats.segments == n_seg
    shed_wf = metrics.get("shed_waterfalls")
    shed_bb = metrics.get("shed_baseband")
    assert shed_wf > 0, "ladder never reached level 1"
    # every segment accounted: pushed to the sink or counted as shed
    assert sink.pushed + shed_bb == n_seg
    assert sink.waterfalls + shed_wf == n_seg
    assert metrics.get("degrade_steps") >= 1
    recs = TR.load(str(tmp_path / "degrade.jsonl"))
    assert len(recs) == n_seg  # no silent loss: all journaled
    assert max(r["degrade_level"] for r in recs) >= 1
    assert recs[-1]["shed_waterfalls"] == shed_wf
    rep = TR.report(str(tmp_path / "degrade.jsonl"))
    assert rep["resilience"]["degrade_level_max"] >= 1
    assert rep["resilience"]["segments_degraded"] >= 1
    metrics.reset()


def test_shed_waterfall_counted_once_across_retries(tmp_path):
    """A retried/replayed sink push re-enters _push_sinks with the
    original waterfall: the shed must not be counted twice."""
    metrics.reset()
    cfg = _watchdog_cfg(tmp_path, "shedonce")
    pipe = Pipeline(cfg, source=_CountingSource(1), sinks=[],
                    processor=_WaterfallProcessor())
    wf = np.zeros((2, 1, 4, 4), np.float32)
    det = _StubDetect(signal_counts=np.zeros((1, 4), np.int64),
                      zero_count=np.asarray(0),
                      time_series=np.zeros(4, np.float32))
    done: set = set()
    pipe._push_sinks(None, wf, det, False, degrade_level=1, done=done)
    pipe._push_sinks(None, wf, det, False, degrade_level=1, done=done)
    assert metrics.get("shed_waterfalls") == 1
    metrics.reset()


# ------------------------------------ restart-after-crash + checkpoint


def test_restart_after_crash_resumes_from_checkpoint(
        synth_file, shared_processor, tmp_path, fault_free_baseline):
    """A fatal fault mid-run kills the pipeline after two checkpointed
    segments; a fresh pipeline on the same config must resume at the
    checkpoint and complete the remainder bit-identically."""
    path, n = synth_file
    base_stats, base_sink = fault_free_baseline
    ck = str(tmp_path / "resume.json")
    metrics.reset()
    cfg = _cfg(path, n, tmp_path, "crash", inflight_segments=1,
               checkpoint_path=ck, fault_plan="dispatch:fatal@2")
    sink_a = _CaptureSink()
    pipe = Pipeline(cfg, sinks=[sink_a], processor=shared_processor)
    with pipe:
        with pytest.raises(InjectedFatal):
            pipe.run()
    assert len(sink_a.detects) == 2  # segments 0, 1 drained + durable
    state = json.load(open(ck))
    assert state["segments_done"] == 2

    # "restart the process": same config, faults cleared
    metrics.reset()
    sink_b = _CaptureSink()
    cfg2 = _cfg(path, n, tmp_path, "crash", inflight_segments=1,
                checkpoint_path=ck)
    with Pipeline(cfg2, sinks=[sink_b],
                  processor=shared_processor) as pipe2:
        stats2 = pipe2.run()
    assert stats2.segments == base_stats.segments - 2
    # the union of both runs is bit-identical to the fault-free run
    combined = _CaptureSink()
    combined.detects = sink_a.detects + sink_b.detects
    combined.positives = sink_a.positives + sink_b.positives
    _assert_same_detects(base_sink, combined)
    metrics.reset()


# ------------------------------------------- crash-consistent outputs


def test_write_bytes_atomic_and_orphan_sweep(tmp_path):
    from srtb_tpu.io.writers import (TMP_SUFFIX, WriteSignalSink,
                                     recover_orphan_temps)

    prefix = str(tmp_path / "cand_")
    cfg = Config(baseband_output_file_prefix=prefix)
    sink = WriteSignalSink(cfg, writer_pool=None)
    path = prefix + "42.bin"
    sink._write_bytes(path, np.arange(16, dtype=np.uint8), fsync=True)
    assert os.path.exists(path)
    assert not os.path.exists(path + TMP_SUFFIX)
    assert np.fromfile(path, np.uint8).tolist() == list(range(16))

    # STALE orphans from an interrupted run are swept; real files and
    # FRESH temps (possibly a live concurrent writer's) survive
    metrics.reset()
    orphan = prefix + "7.npy" + TMP_SUFFIX
    with open(orphan, "wb") as f:
        f.write(b"torn")
    os.utime(orphan, (time.time() - 3600, time.time() - 3600))
    fresh = prefix + "8.npy" + TMP_SUFFIX
    with open(fresh, "wb") as f:
        f.write(b"live writer mid-flush")
    other = str(tmp_path / ("unrelated.bin" + TMP_SUFFIX))
    with open(other, "wb") as f:
        f.write(b"not ours")
    os.utime(other, (time.time() - 3600, time.time() - 3600))
    removed = recover_orphan_temps(prefix)
    assert removed == [orphan]
    assert not os.path.exists(orphan)
    assert os.path.exists(fresh)      # younger than min_age_s: kept
    assert os.path.exists(other)      # different prefix: untouched
    assert os.path.exists(path)       # completed file: untouched
    assert metrics.get("orphan_temps_removed") == 1
    metrics.reset()


def test_pipeline_init_runs_recovery_sweep(tmp_path):
    prefix = str(tmp_path / "out_")
    orphan = prefix + "3.bin.srtb_tmp"
    with open(orphan, "wb") as f:
        f.write(b"torn")
    os.utime(orphan, (time.time() - 3600, time.time() - 3600))
    cfg = Config(baseband_input_count=64,
                 baseband_reserve_sample=False,
                 baseband_output_file_prefix=prefix,
                 writer_thread_count=0)
    pipe = Pipeline(cfg, source=_CountingSource(0), sinks=[],
                    processor=_InstantProcessor())
    pipe.close()
    assert not os.path.exists(orphan)


def test_async_pool_python_fallback_atomic(tmp_path):
    from srtb_tpu.io.native_writer import AsyncWriterPool
    from srtb_tpu.io.writers import TMP_SUFFIX

    path = str(tmp_path / "pool.bin")
    with AsyncWriterPool(1, prefer_native=False) as pool:
        pool.submit(path, np.arange(8, dtype=np.uint8), fsync=True)
        pool.drain()
        assert np.fromfile(path, np.uint8).tolist() == list(range(8))
        assert not os.path.exists(path + TMP_SUFFIX)
        # appends stay in place (no tmp+rename possible)
        with AsyncWriterPool(1, prefer_native=False) as p2:
            p2.submit(path, b"\xff", append=True)
            p2.drain()
        assert os.path.getsize(path) == 9


def test_tmp_suffix_matches_native_pool_literal():
    # native/file_writer.cpp hardcodes ".srtb_tmp": if TMP_SUFFIX ever
    # moved, native-pool temps would silently stop matching the
    # startup sweep and interrupted-run orphans would never be cleaned
    from srtb_tpu.io import writers
    assert writers.TMP_SUFFIX == ".srtb_tmp"
    cpp = os.path.join(os.path.dirname(writers.__file__), "..",
                       "native", "file_writer.cpp")
    with open(cpp) as f:
        assert '".srtb_tmp"' in f.read()


def test_python_fallback_pool_workers_are_daemon(tmp_path):
    # close(drain=False) abandons wedged writes; only DAEMON workers
    # actually die with the process (threading._shutdown joins every
    # non-daemon thread at exit, whatever concurrent.futures does)
    from srtb_tpu.io.native_writer import AsyncWriterPool

    pool = AsyncWriterPool(1, prefer_native=False)
    try:
        pool.submit(str(tmp_path / "d.bin"), b"\x01")
        pool.drain()
        workers = [t for t in threading.enumerate()
                   if t.name.startswith("srtb-writer")]
        assert workers and all(t.daemon for t in workers)
    finally:
        pool.close()
    for t in workers:
        t.join(5.0)
        assert not t.is_alive()


def test_checkpoint_orphan_tmp_removed(tmp_path):
    from srtb_tpu.pipeline.checkpoint import StreamCheckpoint

    ck = str(tmp_path / "ck.json")
    sc = StreamCheckpoint(ck)
    sc.update(3, 300)
    # simulate a crash mid-update: stale tmp next to good state
    with open(ck + ".tmp", "w") as f:
        f.write("{torn")
    sc2 = StreamCheckpoint(ck)
    assert not os.path.exists(ck + ".tmp")
    assert sc2.segments_done == 3 and sc2.file_offset_bytes == 300


# ------------------------------------------------- bounded shutdown


def test_on_exit_bounded_join_reports_wedged():
    from srtb_tpu.pipeline import framework as fw

    metrics.reset()
    release = threading.Event()

    def stuck(stop_token, _):
        release.wait()  # ignores the stop token: a wedged pipe

    stop = fw.StopToken()
    pipe = fw.start_pipe(stuck, None, None, stop, "wedged_pipe")
    t0 = time.monotonic()
    wedged = fw.on_exit(stop, [pipe], timeout=0.25)
    assert time.monotonic() - t0 < 5.0  # bounded, not hanging
    assert wedged == [pipe]
    assert metrics.get("wedged_threads") == 1
    release.set()
    assert pipe.join(5.0)
    metrics.reset()


def test_file_mode_slow_sink_never_sheds(synth_file, shared_processor,
                                         tmp_path):
    """A slow-but-healthy sink flush longer than segment_deadline_s
    must NOT trip the watchdog shed in file mode: shedding is a
    liveness mechanism for real-time sources, while a file-mode run
    throttles losslessly by design (the ladder's documented rule)."""

    class _SlowSink:
        def __init__(self):
            self.pushed = 0

        def push(self, work, positive):
            time.sleep(0.15)  # > deadline: 'slow' must not read 'wedged'
            self.pushed += 1

    path, n = synth_file
    metrics.reset()
    sink = _SlowSink()
    cfg = _cfg(path, n, tmp_path, "slowsink", inflight_segments=2,
               segment_deadline_s=0.05, segment_watchdog_requeues=2)
    pipe = Pipeline(cfg, sinks=[sink], processor=shared_processor)
    with pipe:
        stats = pipe.run()
    assert stats.segments == 4
    assert sink.pushed == 4
    assert metrics.get("segments_dropped") == 0
    metrics.reset()


def test_realtime_slow_multi_sink_flush_is_not_a_wedge(tmp_path):
    """Real-time wedge detection is per-sink-push (the heartbeat), not
    per drained item: two healthy sinks whose COMBINED flush time
    exceeds segment_deadline_s must not be declared wedged — each
    completed push is progress, only a single write stalled past the
    deadline reads as a wedge."""

    class _SlowSink:
        def __init__(self):
            self.pushed = 0

        def push(self, work, positive):
            time.sleep(0.15)  # per-sink < deadline, per-item > deadline
            self.pushed += 1

    metrics.reset()
    sinks = [_SlowSink(), _SlowSink()]
    cfg = _watchdog_cfg(tmp_path, "slowmulti", inflight_segments=2,
                        segment_deadline_s=0.2,
                        segment_watchdog_requeues=2)
    pipe = Pipeline(cfg, source=_CountingSource(4), sinks=sinks,
                    processor=_InstantProcessor())
    with pipe:
        stats = pipe.run()
    assert stats.segments == 4
    assert all(s.pushed == 4 for s in sinks)
    assert metrics.get("segments_dropped") == 0
    metrics.reset()


def test_write_signal_sink_retry_reentry_is_idempotent(tmp_path):
    """A transient failure partway through WriteSignalSink's write makes
    the pipeline's sink_write retry call push() again with the same
    work: the replay must not stamp the overlap window twice nor spill
    the same waterfall under a fresh .npy index."""
    from srtb_tpu.io.writers import WriteSignalSink
    from srtb_tpu.pipeline.work import SegmentResultWork

    class _TimDetect(NamedTuple):
        signal_counts: object
        boxcar_series: object
        boxcar_lengths: tuple

    cfg = Config(baseband_input_count=64, baseband_reserve_sample=False,
                 writer_thread_count=0,
                 baseband_output_file_prefix=str(tmp_path / "idem_"))
    sink = WriteSignalSink(cfg, fdatasync=False)
    # the retried attempt wraps the SAME segment in a FRESH work
    # object, exactly like runtime._push_sinks rebuilding full/light
    # per attempt — idempotency must key on the segment
    seg = SegmentWork(data=np.zeros(64, np.uint8), timestamp=7)

    def mk_work():
        return SegmentResultWork(
            segment=seg,
            # stacked (re, im) x 2 streams -> two .npy files
            waterfall=np.zeros((2, 2, 4, 8), np.float32),
            detect=_TimDetect(
                signal_counts=np.array([[3, 0]], np.int64),
                boxcar_series=np.zeros((1, 2, 8), np.float32),
                boxcar_lengths=(1, 2)))

    # fail the SECOND .npy write (after .bin and the first .npy
    # landed), then let the re-entered push run clean — without the
    # segment-keyed path memo the retry's find-first-free scan sees
    # its own partial output and duplicates stream 0 as .1.npy
    orig = sink._write_bytes
    state = {"fails_left": 1}

    def flaky(path, data, **kw):
        if path.endswith(".1.npy") and state["fails_left"]:
            state["fails_left"] -= 1
            raise TimeoutError("transient disk hiccup")
        return orig(path, data, **kw)

    sink._write_bytes = flaky
    with pytest.raises(TimeoutError):
        sink.push(mk_work(), True)
    sink.push(mk_work(), True)  # the retry re-entry
    assert list(sink.recent_positive_timestamps) == [7]
    npys = sorted(p.name for p in tmp_path.glob("idem_*.npy"))
    assert npys == ["idem_7.0.npy", "idem_7.1.npy"]  # no .2.npy spill
    assert len(sink.written) == 1


def test_write_signal_sink_retry_keeps_piggyback_candidate(tmp_path):
    """A transient failure writing a piggybacked negative (popped off
    the re-check deque) must not lose it: the retry re-entry has to
    find it still scheduled, write it exactly once, and leave the
    OTHER queued negatives for their own turn."""
    from srtb_tpu.io.writers import WriteSignalSink
    from srtb_tpu.pipeline.work import SegmentResultWork

    cfg = Config(baseband_input_count=64, baseband_reserve_sample=False,
                 writer_thread_count=0,
                 baseband_output_file_prefix=str(tmp_path / "piggy_"))
    sink = WriteSignalSink(cfg, fdatasync=False)
    w = sink._overlap_window_ns()

    def negative(ts, counter):
        return SegmentResultWork(
            segment=SegmentWork(data=np.zeros(64, np.uint8),
                                timestamp=ts, udp_packet_counter=counter),
            waterfall=None, detect=None)

    # a positive at ts=10*w anchors the overlap window; work_2 (within
    # the window) is the piggyback candidate, work_3 is not
    base_ts = int(10 * w)
    sink.recent_positive_timestamps.append(base_ts)
    work_2 = negative(base_ts + int(0.5 * w), 21)
    work_3 = negative(base_ts + int(3 * w), 22)
    sink.recent_negative_works.extend([work_2, work_3])

    orig = sink._write_bytes
    state = {"fails_left": 1}

    def flaky(path, data, **kw):
        if state["fails_left"]:
            state["fails_left"] -= 1
            raise TimeoutError("transient disk hiccup")
        return orig(path, data, **kw)

    sink._write_bytes = flaky
    trigger = negative(base_ts + int(2 * w), 23)  # outside the window
    with pytest.raises(TimeoutError):
        sink.push(trigger, False)
    # retry re-entry, fresh work wrapper around the same segment
    sink.push(SegmentResultWork(segment=trigger.segment,
                                waterfall=None, detect=None), False)
    assert [c.bin_path for c in sink.written] \
        == [str(tmp_path / "piggy_21.bin")]
    remaining = [wk.segment.udp_packet_counter
                 for wk in sink.recent_negative_works]
    assert 22 in remaining  # work_3 was not mis-scheduled by the retry
    metrics.reset()


def test_pipeline_shutdown_join_is_bounded(tmp_path):
    """A sink wedged on an external resource must not hang run()'s
    shutdown forever: the bounded join expires, reports, and returns
    (the watchdog shed already accounted the stuck segment)."""

    class _WedgedSink:
        def __init__(self):
            self.release = threading.Event()
            self.entered = threading.Event()

        def push(self, work, positive):
            self.entered.set()
            self.release.wait()

    metrics.reset()
    sink = _WedgedSink()
    cfg = _watchdog_cfg(tmp_path, "wedge", inflight_segments=2,
                        segment_deadline_s=0.12,
                        segment_watchdog_requeues=1,
                        shutdown_join_timeout_s=0.25)
    pipe = Pipeline(cfg, source=_CountingSource(4), sinks=[sink],
                    processor=_InstantProcessor())
    t0 = time.monotonic()
    with pipe:
        stats = pipe.run()
    assert time.monotonic() - t0 < 20.0
    assert sink.entered.is_set()
    # full accounting, no silent loss: of the 4 produced segments, the
    # one wedged inside the sink (never journaled) and the one parked
    # on the sink queue were accounted as dropped at shutdown, and the
    # two the engine could no longer admit were shed at ingest as
    # accounted loss (the never-stall property); the join stayed
    # bounded throughout
    from srtb_tpu.tools import telemetry_report as TR

    dropped = metrics.get("segments_dropped")
    journaled = len(TR.load(str(tmp_path / "wedge.jsonl")))
    assert stats.segments == 2      # A, B dispatched before the wedge
    assert dropped == 4             # A (wedged), B (queued), C, D (shed)
    assert journaled == 0           # nothing fully drained
    assert journaled + dropped == 4  # every produced segment accounted
    assert metrics.get("wedged_threads") >= 1
    # handoff: the wedged worker unwedging AFTER shutdown accounted
    # its segment as dropped must not ALSO journal/count it (double
    # account) or re-release the live slot (gauge going negative)
    sink.release.set()
    deadline = time.monotonic() + 5.0
    while any(t.name == "sink_drain" and t.is_alive()
              for t in threading.enumerate()) \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert metrics.get("segments_dropped") == 4
    assert len(TR.load(str(tmp_path / "wedge.jsonl"))) == 0
    assert metrics.get("inflight_depth") == 0
    metrics.reset()


def test_threaded_completion_join_not_truncated_by_budget(tmp_path):
    """ThreadedPipeline's wait-for-completion lasts the whole run: a
    healthy observation longer than shutdown_join_timeout_s must NOT
    be cut short — the budget bounds only a wedged drain (busy on one
    item with zero per-sink progress), not slow-but-steady work."""
    from srtb_tpu.pipeline.runtime import ThreadedPipeline

    class _SlowSink:
        def __init__(self):
            self.pushed = 0

        def push(self, work, positive):
            time.sleep(0.1)
            self.pushed += 1

    metrics.reset()
    sink = _SlowSink()
    cfg = _watchdog_cfg(tmp_path, "tcomplete",
                        shutdown_join_timeout_s=0.3)
    pipe = ThreadedPipeline(cfg, source=_CountingSource(8), sinks=[sink],
                            processor=_InstantProcessor())
    with pipe:
        stats = pipe.run()  # total sink time ~0.8s > the 0.3s budget
    assert stats.segments == 8
    assert sink.pushed == 8
    assert metrics.get("segments_dropped") == 0
    metrics.reset()


def test_threaded_shutdown_join_is_bounded_on_wedged_sink(tmp_path):
    """...but a ThreadedPipeline drain wedged inside one sink write
    still must not hang run() forever."""
    from srtb_tpu.pipeline.runtime import ThreadedPipeline

    class _WedgedSink:
        def __init__(self):
            self.release = threading.Event()
            self.entered = threading.Event()

        def push(self, work, positive):
            self.entered.set()
            self.release.wait()

    metrics.reset()
    sink = _WedgedSink()
    cfg = _watchdog_cfg(tmp_path, "twedge",
                        shutdown_join_timeout_s=0.25)
    pipe = ThreadedPipeline(cfg, source=_CountingSource(3), sinks=[sink],
                            processor=_InstantProcessor())
    t0 = time.monotonic()
    with pipe:
        pipe.run()
    assert time.monotonic() - t0 < 20.0
    assert sink.entered.is_set()
    sink.release.set()
    metrics.reset()


# ------------------------------------------------- mixed v2/v3 journal


def test_telemetry_report_tolerates_mixed_v2_v3(tmp_path):
    """Rotation can leave a v2 tail next to v3 records: stages cover
    both, the resilience section only the v3 ones."""
    from srtb_tpu.tools import telemetry_report as TR

    path = tmp_path / "mixed23.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({
            "type": "segment_span", "v": 2, "ts": 1000.0, "segment": 0,
            "stages_ms": {"dispatch": 2.0, "fetch": 1.0},
            "queue_depth": 1, "detections": 0, "dump": False,
            "samples": 64, "overlap_hidden_ms": 3.0,
            "inflight_depth": 2}) + "\n")
        f.write(json.dumps({
            "type": "segment_span", "v": 3, "ts": 1001.0, "segment": 1,
            "stages_ms": {"dispatch": 2.0, "fetch": 1.0},
            "queue_depth": 1, "detections": 0, "dump": False,
            "samples": 64, "overlap_hidden_ms": 3.0,
            "inflight_depth": 2, "degrade_level": 1, "retries": 4,
            "requeues": 1, "restarts": 0, "shed_waterfalls": 2,
            "shed_baseband": 0}) + "\n")
    rep = TR.report(str(path))
    assert rep["records"] == 2
    assert rep["stages"]["dispatch"]["count"] == 2
    assert rep["overlap"]["records"] == 2
    rs = rep["resilience"]
    assert rs["records"] == 1
    assert rs["retries"] == 4 and rs["requeues"] == 1
    assert rs["degrade_level_max"] == 1 and rs["segments_degraded"] == 1
    md = TR._md(rep)
    assert "## Resilience" in md
    assert TR.main([str(path), "--format", "json"]) == 0


def test_telemetry_report_tolerates_mixed_v2_v3_v4(tmp_path):
    """A v4 upgrade mid-rotation: stages cover every record, the
    resilience section the v3+v4 ones, the compute-health section
    only the v4 ones — and the active-plan timeline reads change
    points off the v4 tail."""
    from srtb_tpu.tools import telemetry_report as TR

    path = tmp_path / "mixed234.jsonl"
    base = {"type": "segment_span", "queue_depth": 1, "detections": 0,
            "dump": False, "samples": 64,
            "stages_ms": {"dispatch": 2.0, "fetch": 1.0},
            "overlap_hidden_ms": 3.0, "inflight_depth": 2}
    with open(path, "w") as f:
        f.write(json.dumps({**base, "v": 2, "ts": 1000.0,
                            "segment": 0}) + "\n")
        f.write(json.dumps({**base, "v": 3, "ts": 1001.0, "segment": 1,
                            "degrade_level": 0, "retries": 2,
                            "requeues": 0, "restarts": 0,
                            "shed_waterfalls": 0,
                            "shed_baseband": 0}) + "\n")
        for seg, plan, dem, lvl in ((2, "fused:four_step+ring", 0, 0),
                                    (3, "fused:four_step", 1, 1),
                                    (4, "fused:four_step", 1, 1)):
            f.write(json.dumps({
                **base, "v": 4, "ts": 1002.0 + seg, "segment": seg,
                "degrade_level": 0, "retries": 2, "requeues": 0,
                "restarts": 0, "shed_waterfalls": 0,
                "shed_baseband": 0, "plan_demotions": dem,
                "plan_promotions": 0, "device_reinits": 0,
                "plan_ladder_level": lvl,
                "active_plan": plan}) + "\n")
    rep = TR.report(str(path))
    assert rep["records"] == 5
    assert rep["stages"]["dispatch"]["count"] == 5
    assert rep["resilience"]["records"] == 4  # v3 + v4
    cs = rep["compute"]
    assert cs["records"] == 3  # v4 only
    assert cs["plan_demotions"] == 1 and cs["device_reinits"] == 0
    assert cs["ladder_level_max"] == 1 and cs["segments_demoted"] == 2
    assert cs["plan_timeline"] == [
        {"segment": 2, "plan": "fused:four_step+ring"},
        {"segment": 3, "plan": "fused:four_step"}]
    md = TR._md(rep)
    assert "## Compute health" in md
    assert "fused:four_step+ring" in md
    assert TR.main([str(path), "--format", "json"]) == 0


# (the repo-wide swallowed-except acceptance rides the existing
# test_lint.py::test_repo_lints_clean_against_baseline, which runs
# EVERY rule — including the new one — against the checked-in
# baseline; no duplicate whole-repo lint pass here)
