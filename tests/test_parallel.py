"""Multi-chip tests on the virtual 8-device CPU mesh: DM-trial grid and the
fully sharded ("dm", "seq") segment step, cross-checked against the
single-device SegmentProcessor (self-consistency oracle, the strategy the
reference uses for generic-vs-handwritten kernels)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.ops import dedisperse as dd
from srtb_tpu.parallel import dm_grid, mesh as M
from srtb_tpu.parallel.segment_dist import DistSegmentProcessor
from srtb_tpu.pipeline.segment import SegmentProcessor
from srtb_tpu.io.synth import make_dispersed_baseband


def _cfg(tmpdir="", n=1 << 14, dm=30.0):
    return Config(
        baseband_input_count=n,
        baseband_input_bits=8,
        baseband_format_type="simple",
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        dm=dm,
        spectrum_channel_count=1 << 6,
        signal_detect_signal_noise_threshold=6.0,
        signal_detect_max_boxcar_length=32,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        baseband_reserve_sample=False,
    )


@pytest.fixture(scope="module")
def raw_segment():
    cfg = _cfg()
    return make_dispersed_baseband(
        cfg.baseband_input_count, cfg.baseband_freq_low,
        cfg.baseband_bandwidth, cfg.dm,
        pulse_positions=cfg.baseband_input_count // 2, pulse_amp=25.0)


def test_dm_grid_finds_true_dm(raw_segment):
    """8 DM trials across 8 chips; the trial nearest the true DM must give
    the highest peak SNR."""
    cfg = _cfg()
    mesh = M.dm_mesh(8)
    proc = SegmentProcessor(cfg.replace(dm=0.0))
    # spectrum before dedispersion: run stage-1 part manually
    from srtb_tpu.ops import fft as F, rfi, unpack as U
    x = U.unpack(jnp.asarray(raw_segment), 8)
    spec = F.segment_rfft(x)
    spec = rfi.mitigate_rfi_average_and_normalize(
        spec, cfg.mitigate_rfi_average_method_threshold, proc.norm_coeff)
    spec = jnp.stack([jnp.real(spec), jnp.imag(spec)])

    dm_list = [0.0, 10.0, 20.0, 30.0, 40.0, 50.0, 60.0, 70.0]
    f_min, f_c, df = dd.spectrum_frequencies(cfg, proc.n_spectrum)
    bank = dm_grid.build_chirp_bank(dm_list, proc.n_spectrum, f_min, df, f_c,
                                    mesh=mesh)
    res = dm_grid.dm_trial_search(
        spec, bank, dm_list, mesh,
        channel_count=proc.channel_count,
        time_reserved_count=0,
        snr_threshold=6.0,
        max_boxcar_length=32,
        sk_threshold=cfg.mitigate_rfi_spectral_kurtosis_threshold)
    idx, snr = dm_grid.best_trial(res)
    assert dm_list[idx] == 30.0, \
        f"best dm {dm_list[idx]} snr {snr}, peaks={np.asarray(res.snr_peaks).max(axis=-1)}"

    # len_cap threads through to the trial waterfalls (Config.fft_len_cap
    # contract): forcing the in-trial four-step recursion must not
    # change any detection outcome
    res_cap = dm_grid.dm_trial_search(
        spec, bank, dm_list, mesh,
        channel_count=proc.channel_count,
        time_reserved_count=0,
        snr_threshold=6.0,
        max_boxcar_length=32,
        sk_threshold=cfg.mitigate_rfi_spectral_kurtosis_threshold,
        len_cap=1 << 4)
    np.testing.assert_allclose(
        np.asarray(res_cap.snr_peaks), np.asarray(res.snr_peaks),
        rtol=2e-4, atol=1e-3)


def test_chirp_bank_on_device_matches_host():
    mesh = M.dm_mesh(8)
    dm_list = np.linspace(10.0, 80.0, 8)
    n = 1 << 10
    host = dm_grid.build_chirp_bank(dm_list, n, 1405.0, 64.0 / n, 1469.0,
                                    mesh=mesh)
    dev = dm_grid.build_chirp_bank(dm_list, n, 1405.0, 64.0 / n, 1469.0,
                                   mesh=mesh, on_device=True)
    err = np.abs(np.angle(np.asarray(dev) * np.conj(np.asarray(host))))
    assert np.max(err) < 5e-3


def test_dist_segment_matches_single_device(raw_segment):
    """The ("dm", "seq")-sharded step must reproduce the single-device
    pipeline's detection outputs for the same DM."""
    cfg = _cfg()
    single = SegmentProcessor(cfg)
    wf, res_single = single.process(raw_segment)

    mesh = M.make_mesh(n_dm=2, n_seq=4)
    dist = DistSegmentProcessor(cfg, mesh, dm_list=[cfg.dm, 0.0])
    res = dist.process(raw_segment)

    counts_single = np.asarray(res_single.signal_counts)[0]
    counts_dist = np.asarray(res.signal_counts)[0, 0]  # dm 0, stream 0
    np.testing.assert_array_equal(counts_dist, counts_single)
    assert int(np.asarray(res.zero_count)[0, 0]) == \
        int(np.asarray(res_single.zero_count)[0])
    np.testing.assert_allclose(np.asarray(res.time_series)[0, 0],
                               np.asarray(res_single.time_series)[0],
                               rtol=2e-3, atol=1e-2)
    # trial at dm=0 must be weaker than the matched trial
    assert np.asarray(res.snr_peaks)[0].max() > \
        np.asarray(res.snr_peaks)[1].max()


def test_dist_segment_seq_only(raw_segment):
    """Pure sequence sharding (seq=8, dm=1)."""
    cfg = _cfg()
    mesh = M.make_mesh(n_dm=1, n_seq=8)
    dist = DistSegmentProcessor(cfg, mesh)
    res = dist.process(raw_segment)
    assert np.asarray(res.signal_counts).shape[0] == 1
    assert np.asarray(res.signal_counts).sum() > 0  # pulse found


def test_dm_search_pipeline(tmp_path):
    """File -> DMSearchPipeline over an 8-trial grid on the 8-device mesh:
    the best trial per segment must be the injected DM."""
    cfg = _cfg().replace(
        dm_list=[0.0, 10.0, 20.0, 30.0, 40.0, 50.0, 60.0, 70.0],
        baseband_output_file_prefix=str(tmp_path / "dm_"),
        signal_detect_signal_noise_threshold=7.0,
    )
    raw = make_dispersed_baseband(
        cfg.baseband_input_count, cfg.baseband_freq_low,
        cfg.baseband_bandwidth, 30.0,
        pulse_positions=cfg.baseband_input_count // 2, pulse_amp=25.0)
    path = str(tmp_path / "in.bin")
    raw.tofile(path)
    cfg = cfg.replace(input_file_path=path)

    from srtb_tpu.pipeline.runtime import DMSearchPipeline
    import json
    pipe = DMSearchPipeline(cfg)
    stats = pipe.run()
    assert stats.segments == 1
    with open(pipe.trials_path) as f:
        rec = json.loads(f.readline())
    assert rec["best_dm"] == 30.0
    assert rec["best_snr"] > 7.0


def test_dist_segment_two_streams():
    """Multi-stream (2-pol interleaved) distributed step: both polarization
    streams flow through the sharded FFT/detect chain."""
    cfg = _cfg().replace(baseband_format_type="interleaved_samples_2")
    rng = np.random.default_rng(0)
    raw = rng.integers(0, 256,
                       size=cfg.baseband_input_count * 2,
                       dtype=np.uint8)
    mesh = M.make_mesh(n_dm=2, n_seq=4)
    dist = DistSegmentProcessor(cfg, mesh, dm_list=[0.0, 10.0])
    res = dist.process(raw)
    assert np.asarray(res.signal_counts).shape[:2] == (2, 2)  # [n_dm, S]
    assert np.asarray(res.time_series).shape[:2] == (2, 2)

    # cross-check stream results against the single-device processor
    single = SegmentProcessor(cfg.replace(dm=0.0))
    _, res_single = single.process(raw)
    np.testing.assert_array_equal(
        np.asarray(res.signal_counts)[0],
        np.asarray(res_single.signal_counts))


def test_dist_segment_window_matches_single_device(raw_segment):
    """A configured non-rectangle window must flow through the multi-chip
    step too — applied at unpack on each device's seq-shard and divided
    back out of the waterfall — matching the single-chip windowed run."""
    cfg = _cfg()
    single = SegmentProcessor(cfg, window_name="hamming")
    _, res_single = single.process(raw_segment)

    mesh = M.make_mesh(n_dm=2, n_seq=4)
    dist = DistSegmentProcessor(cfg, mesh, dm_list=[cfg.dm, 0.0],
                                window_name="hamming")
    res = dist.process(raw_segment)

    np.testing.assert_array_equal(
        np.asarray(res.signal_counts)[0, 0],
        np.asarray(res_single.signal_counts)[0])
    np.testing.assert_allclose(np.asarray(res.time_series)[0, 0],
                               np.asarray(res_single.time_series)[0],
                               rtol=2e-3, atol=1e-2)


def test_dist_segment_chirp_on_device_matches_bank(raw_segment):
    """On-the-fly df64 chirp generation inside the sharded step (no HBM
    chirp bank) must reproduce the host-f64 bank's detections."""
    cfg = _cfg()
    mesh = M.make_mesh(n_dm=2, n_seq=4)
    dms = [0.0, 15.0, 30.0, 45.0]
    bank = DistSegmentProcessor(cfg, mesh, dm_list=dms,
                                chirp_on_device=False)
    otf = DistSegmentProcessor(cfg, mesh, dm_list=dms,
                               chirp_on_device=True)
    res_a = bank.process(raw_segment)
    res_b = otf.process(raw_segment)
    np.testing.assert_array_equal(np.asarray(res_a.zero_count),
                                  np.asarray(res_b.zero_count))
    np.testing.assert_allclose(np.asarray(res_a.time_series),
                               np.asarray(res_b.time_series),
                               rtol=2e-3, atol=2e-2)
    np.testing.assert_array_equal(np.asarray(res_a.signal_counts),
                                  np.asarray(res_b.signal_counts))


def test_dist_rejects_non_dividing_channel_count():
    """Non-power-of-two channel counts that don't divide the spectrum
    truncate on the single-chip path but would straddle a shard boundary
    distributed — the round-3 sweep caught this as a cryptic reshape
    failure deep inside shard_map; it must be a clear constructor error."""
    cfg = Config(
        baseband_input_count=1 << 14, baseband_input_bits=2,
        baseband_format_type="simple", baseband_freq_low=1405.0,
        baseband_bandwidth=64.0, baseband_sample_rate=128e6, dm=5.0,
        spectrum_channel_count=48, signal_detect_max_boxcar_length=8,
        baseband_reserve_sample=False)
    mesh = M.make_mesh(n_dm=2, n_seq=2, devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="must divide"):
        DistSegmentProcessor(cfg, mesh, dm_list=[1.0, 2.0, 3.0, 4.0])


def test_dist_rows_impl_knob(raw_segment, monkeypatch):
    """SRTB_DIST_ROWS_IMPL=pallas must reach the distributed leg FFTs
    (as pallas_interpret off-TPU), keep the step's outputs on-plan, and
    reject typos loudly."""
    from srtb_tpu.ops import fft as F

    cfg = _cfg()
    mesh = M.make_mesh(n_dm=2, n_seq=4)
    monkeypatch.delenv("SRTB_DIST_ROWS_IMPL", raising=False)
    base = DistSegmentProcessor(cfg, mesh, dm_list=[cfg.dm, 0.0])
    res_base = base.process(raw_segment)

    impls_seen = []
    orig = F._fft_minor

    def spy(x, inverse, rows_impl="xla", len_cap=None):
        impls_seen.append(rows_impl)
        return orig(x, inverse, rows_impl, len_cap)

    monkeypatch.setenv("SRTB_DIST_ROWS_IMPL", "pallas")
    monkeypatch.setattr(F, "_fft_minor", spy)
    try:
        import srtb_tpu.parallel.dist_fft as DF
        monkeypatch.setattr(DF, "_fft_minor", spy)
        dist = DistSegmentProcessor(cfg, mesh, dm_list=[cfg.dm, 0.0])
        res = dist.process(raw_segment)
    finally:
        monkeypatch.setattr(F, "_fft_minor", orig)
    assert "pallas_interpret" in impls_seen, impls_seen
    np.testing.assert_array_equal(np.asarray(res.signal_counts),
                                  np.asarray(res_base.signal_counts))

    monkeypatch.setenv("SRTB_DIST_ROWS_IMPL", "palas")
    with pytest.raises(ValueError, match="SRTB_DIST_ROWS_IMPL"):
        DistSegmentProcessor(cfg, mesh, dm_list=[cfg.dm, 0.0])


def _collect_collectives(jaxpr, out):
    """(primitive name, mesh axes) of every collective in a jaxpr tree."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in ("all_to_all", "ppermute", "all_gather",
                    "reduce_scatter") or "psum" in name:
            ax = eqn.params.get("axes") or eqn.params.get("axis_name")
            ax = (ax,) if isinstance(ax, str) else tuple(ax)
            out.append((name.replace("psum_invariant", "psum"), ax))
        for v in eqn.params.values():
            for item in (v if isinstance(v, (list, tuple)) else [v]):
                if hasattr(item, "jaxpr"):
                    _collect_collectives(item.jaxpr, out)
                elif hasattr(item, "eqns"):
                    _collect_collectives(item, out)
    return out


def test_dist_step_collective_inventory(raw_segment):
    """The module docstring's collective inventory, enforced: 3 a2a(seq)
    + 2 ppermute(seq) + 3 psum(seq) + 3 psum(dm) per segment.  A change
    that silently adds a collective (an accidental replication, a
    sharding-constraint round trip) must fail here, not surface as an
    unexplained ICI regression on hardware (round-3 verdict #7)."""
    from collections import Counter

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = _cfg()
    mesh = M.make_mesh(n_dm=2, n_seq=4)
    dist = DistSegmentProcessor(cfg, mesh, dm_list=[cfg.dm, 0.0])
    raw = jax.device_put(np.zeros(cfg.segment_bytes(1), np.uint8),
                         NamedSharding(mesh, P("seq")))
    args = [raw, dist.chirp_bank, dist.rfi_mask]
    if dist.window is not None:
        args.append(dist.window)
    jaxpr = jax.make_jaxpr(dist._step)(*args)
    got = Counter(_collect_collectives(jaxpr.jaxpr, []))
    assert got == Counter({
        ("all_to_all", ("seq",)): 3,
        ("ppermute", ("seq",)): 2,
        ("psum", ("seq",)): 3,
        ("psum", ("dm",)): 3,
    }), got
