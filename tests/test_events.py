"""Causal segment tracing, the flight recorder, incident bundles, the
Chrome-trace exporter, and SLO burn-rate evaluation (ISSUE 13).

Unit layer: the event hub's ring/shard/merge mechanics and zero-cost
off contract, the SLO burn math under an injected clock, incident
rate/count bounds.  E2E layer: a CPU pipeline run whose every segment
leaves a complete causal chain across the engine/sink thread boundary,
a seeded escalation that produces exactly one incident bundle holding
the injected fault site, its classification, the heal decisions and
the affected segment's manifest disposition, and the exporter's
structural Chrome-trace guarantees."""

import json
import os
import threading

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.utils import events, slo, telemetry
from srtb_tpu.utils.events import EventHub
from srtb_tpu.utils.incidents import IncidentRecorder
from srtb_tpu.utils.metrics import metrics
from srtb_tpu.utils.slo import SloTracker


@pytest.fixture(autouse=True)
def _fresh_observability():
    """Each test gets a fresh hub/registry/SLO world (they are
    process-global by design)."""
    events.configure(False)  # drop any previous test's shards...
    events.configure(True, ring_size=events.DEFAULT_RING_SIZE)
    metrics.reset()
    slo.reset()
    yield
    events.configure(False)
    events.configure(True, ring_size=events.DEFAULT_RING_SIZE)
    metrics.reset()
    slo.reset()


# ------------------------------------------------------------- hub units


def test_ring_bounded_no_growth():
    """The flight recorder is O(ring size): overwriting slots, never
    growing — 10x ring_size emits leave exactly ring_size slots and
    only the newest events."""
    hub = EventHub(ring_size=64)
    for i in range(640):
        hub.emit("stage.ingest", trace=i, seg=i)
    sh = hub._tls.shard
    assert sh.n == 64 and len(sh.slots) == 64
    evs = hub.dump()
    assert len(evs) == 64
    assert [e["trace"] for e in evs] == list(range(576, 640))


def test_shards_merge_across_threads_ordered():
    hub = EventHub(ring_size=128)
    hub.emit("stage.ingest", trace=1)

    def worker():
        hub.emit("stage.sink", trace=1)

    t = threading.Thread(target=worker, name="shard-worker")
    t.start()
    t.join()
    hub.emit("stage.fetch", trace=1)
    evs = hub.dump()
    assert [e["type"] for e in evs] == ["stage.ingest", "stage.sink",
                                       "stage.fetch"]  # by time
    assert {e["thread"] for e in evs} == {
        threading.current_thread().name, "shard-worker"}
    # per-trace filter
    assert hub.dump(trace=2) == []
    assert len(hub.dump(trace=1)) == 3


def test_zero_cost_off_and_configure_keeps_ring():
    events.configure(False)
    assert events.hub is None
    events.emit("stage.ingest", trace=1)  # no-op, no raise
    events.configure(True, ring_size=256)
    events.emit("retry", trace=7, info="x")
    # re-arming with the same ring KEEPS the recorder (a fleet
    # constructing N lanes must not wipe it N times)
    events.configure(True, ring_size=256)
    assert [e["trace"] for e in events.hub.dump()] == [7]
    # a different ring size rebuilds
    events.configure(True, ring_size=128)
    assert events.hub.dump() == []


def test_ambient_context_attribution():
    events.set_current(42, "beamX")
    events.emit("retry", info="dispatch:transient:1")
    events.emit("manifest.intent", trace=3, stream="other")
    evs = events.hub.dump()
    assert evs[0]["trace"] == 42 and evs[0]["stream"] == "beamX"
    assert evs[1]["trace"] == 3 and evs[1]["stream"] == "other"


def test_dump_jsonl_roundtrip(tmp_path):
    events.emit("stage.dispatch", trace=5, seg=2, dur=0.01, info="z")
    path = str(tmp_path / "ev" / "events.jsonl")
    n = events.hub.dump_jsonl(path)
    assert n == 1
    rec = json.loads(open(path).read().strip())
    assert rec["type"] == "stage.dispatch" and rec["trace"] == 5
    assert rec["dur_ms"] == 10.0 and rec["seg"] == 2
    assert "ts" in rec and "thread" in rec


# ------------------------------------------------------ pipeline helpers


def _mk_cfg(tmp_path, tag, n=1 << 14, **kw):
    from srtb_tpu.io.synth import make_dispersed_baseband
    bb = tmp_path / f"{tag}.bin"
    if not bb.exists():
        make_dispersed_baseband(n * 4, 1405.0, 64.0, 0.0,
                                pulse_positions=n // 2, pulse_amp=30.0,
                                nbits=8).tofile(str(bb))
    return Config(baseband_input_count=n, baseband_input_bits=8,
                  baseband_freq_low=1405.0, baseband_bandwidth=64.0,
                  baseband_sample_rate=128e6,
                  input_file_path=str(bb),
                  baseband_output_file_prefix=str(tmp_path / f"{tag}_"),
                  spectrum_channel_count=1 << 6,
                  mitigate_rfi_average_method_threshold=100.0,
                  mitigate_rfi_spectral_kurtosis_threshold=2.0,
                  baseband_reserve_sample=False, writer_thread_count=0,
                  retry_backoff_base_s=0.001,
                  **dict({"inflight_segments": 3}, **kw))


# --------------------------------------------------------- e2e causality


def test_pipeline_causal_chain_across_threads(tmp_path):
    """Every drained segment owns a distinct trace_id whose event
    chain runs ingest -> dispatch -> fetch -> sink in time order, with
    the sink stage on the sink-pipe thread (the boundary the flow
    arrows cross), and the journal span carries the same trace_id."""
    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.tools import telemetry_report as TR

    journal = str(tmp_path / "j.jsonl")
    cfg = _mk_cfg(tmp_path, "chain",
                  telemetry_journal_path=journal,
                  events_dump_path=str(tmp_path / "events.jsonl"))
    with Pipeline(cfg, sinks=[]) as pipe:
        stats = pipe.run()
    assert stats.segments >= 3
    evs = events.hub.dump()
    by_trace = {}
    for e in evs:
        if e["type"].startswith("stage."):
            by_trace.setdefault(e["trace"], []).append(e)
    assert len(by_trace) == stats.segments
    assert all(t > 0 for t in by_trace)
    for chain in by_trace.values():
        assert [e["type"] for e in chain] == [
            "stage.ingest", "stage.dispatch", "stage.fetch",
            "stage.sink"]
        assert all(e["dur_ms"] >= 0 for e in chain)
        # the sink stage ran on the sink pipe thread — the causal
        # chain crosses the thread boundary
        assert chain[3]["thread"] != chain[0]["thread"]
        assert chain[3]["thread"].startswith("sink_drain")
    # v8 journal spans join the recorder on trace_id
    recs = TR.load(journal)
    assert [r["v"] for r in recs] == [11] * stats.segments
    assert sorted(r["trace_id"] for r in recs) == sorted(by_trace)
    # the run-end dump landed for the exporter
    assert os.path.exists(str(tmp_path / "events.jsonl"))


def test_events_disabled_run_is_clean(tmp_path):
    """events_enable=0: no trace stamping, no events, spans omit
    trace_id — and the run completes identically."""
    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.tools import telemetry_report as TR

    journal = str(tmp_path / "j.jsonl")
    cfg = _mk_cfg(tmp_path, "off", events_enable=False,
                  telemetry_journal_path=journal)
    with Pipeline(cfg, sinks=[]) as pipe:
        stats = pipe.run()
    assert stats.segments >= 3
    assert events.hub is None
    for r in TR.load(journal):
        assert "trace_id" not in r


def test_retry_event_attributed_to_segment(tmp_path):
    """A dispatch-site retry lands on the flight recorder carrying the
    faulted segment's trace id (ambient-context attribution)."""
    from srtb_tpu.pipeline.runtime import Pipeline

    cfg = _mk_cfg(tmp_path, "retry", fault_plan="dispatch:raise@1")
    with Pipeline(cfg, sinks=[]) as pipe:
        pipe.run()
        assert pipe.faults.unfired() == []
    evs = events.hub.dump()
    retries = [e for e in evs if e["type"] == "retry"]
    injected = [e for e in evs if e["type"] == "fault.injected"]
    assert len(retries) == 1 and len(injected) == 1
    assert retries[0]["info"].startswith("dispatch:transient:")
    # both carry segment 1's trace (= the dispatch stage event that
    # eventually succeeded for seg index 1)
    seg1 = [e for e in evs if e["type"] == "stage.dispatch"
            and e["seg"] == 1]
    assert seg1 and retries[0]["trace"] == seg1[0]["trace"] > 0
    assert injected[0]["trace"] == seg1[0]["trace"]


# ------------------------------------------------------ incident bundles


def test_escalation_writes_one_bundle_with_causal_story(tmp_path):
    """The acceptance gate: a seeded device-fault escalation produces
    exactly ONE incident bundle whose causal evidence holds the
    injected fault site, its classification, every heal/demote
    decision, and the affected segment's manifest disposition."""
    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.resilience.errors import LadderExhausted

    inc_dir = str(tmp_path / "incidents")
    cfg = _mk_cfg(
        tmp_path, "esc",
        fault_plan="dispatch:oom@1,fetch:oom@2",
        # exactly one real rung: the staged demotion (the base plan
        # resolves unstaged at this size) — the first oom demotes,
        # the second exhausts the ladder.  Serial window: segment 0
        # fully drains (manifest commit + ckpt) BEFORE the faults, so
        # the bundle deterministically holds the WAL's disposition.
        plan_ladder="staged", device_reinit_max=0,
        inflight_segments=1,
        incident_dir=inc_dir,
        checkpoint_path=str(tmp_path / "esc_ck.json"),
        run_manifest_path=str(tmp_path / "esc_manifest.wal"),
        telemetry_journal_path=str(tmp_path / "esc_j.jsonl"))
    with pytest.raises(LadderExhausted), \
            Pipeline(cfg) as pipe:
        pipe.run()
    bundles = [d for d in os.listdir(inc_dir)
               if d.startswith("incident_")]
    assert len(bundles) == 1, bundles
    assert "ladder_exhausted" in bundles[0]
    b = os.path.join(inc_dir, bundles[0])
    names = set(os.listdir(b))
    assert {"incident.json", "events.jsonl", "trace.jsonl",
            "plan.json", "config.json", "metrics.json"} <= names
    meta = json.load(open(os.path.join(b, "incident.json")))
    assert meta["kind"] == "ladder_exhausted"
    offender = meta["trace_id"]
    assert offender > 0
    evs = [json.loads(ln) for ln in open(os.path.join(b,
                                                      "events.jsonl"))]
    types = [e["type"] for e in evs]
    # the injected fault site fired, twice
    fired = [e for e in evs if e["type"] == "fault.injected"]
    assert len(fired) == 2
    assert any("dispatch:oom@1" in e["info"] for e in fired)
    assert any("fetch:oom@2" in e["info"] for e in fired)
    # classification + every heal decision
    assert types.count("fault.device") == 2
    demotes = [e for e in evs if e["type"] == "heal.demote"]
    assert len(demotes) == 1 and demotes[0]["info"].startswith(
        "staged@1")
    # manifest disposition: the WAL's records are on the trace (the
    # run stamps a ckpt consistency point; committed artifacts of
    # earlier segments carry intent/commit/done)
    assert "manifest.ckpt" in types
    # the offending trace's own story is a strict, non-empty subset
    tr = [json.loads(ln) for ln in open(os.path.join(b,
                                                     "trace.jsonl"))]
    assert tr and all(e["trace"] == offender for e in tr)
    assert any(e["type"] == "fault.device" for e in tr)
    # plan identity rode along
    plan = json.load(open(os.path.join(b, "plan.json")))
    assert plan["plan_name"]
    # metrics + config snapshots are JSON objects
    assert json.load(open(os.path.join(b, "metrics.json")))
    assert json.load(open(os.path.join(b, "config.json")))[
        "plan_ladder"] == "staged"
    assert metrics.get("incident_bundles") == 1


def test_incident_rate_limit_and_count_bound(tmp_path):
    rec = IncidentRecorder(str(tmp_path / "inc"), max_bundles=2,
                           min_interval_s=3600.0)
    assert rec.dump("first", reason="a") is not None
    # inside the rate window: suppressed
    assert rec.dump("second", reason="b") is None
    assert metrics.get("incidents_suppressed") == 1
    rec.min_interval_s = 0.0
    assert rec.dump("third", reason="c") is not None
    # count bound: two bundles kept, further dumps suppressed
    assert rec.dump("fourth", reason="d") is None
    assert metrics.get("incident_bundles") == 2
    assert metrics.get("incidents_suppressed") == 2
    names = sorted(os.listdir(str(tmp_path / "inc")))
    assert len(names) == 2
    # sequence numbers monotonic, kinds in the names
    assert names[0].startswith("incident_000_first")
    assert names[1].startswith("incident_001_third")


def test_incident_tmp_swept_on_construction(tmp_path):
    d = tmp_path / "inc"
    d.mkdir()
    stale = d / ("incident_000_x" + ".srtb_tmp")
    stale.mkdir()
    (stale / "partial.json").write_text("{}")
    IncidentRecorder(str(d))
    assert not stale.exists()


# ---------------------------------------------------------- trace export


def test_trace_export_structure_and_flows(tmp_path):
    """Rendered output is valid Chrome-trace JSON; each segment's flow
    chain binds its stage slices across the thread boundary."""
    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.tools import trace_export as TE

    dump = str(tmp_path / "events.jsonl")
    cfg = _mk_cfg(tmp_path, "export", events_dump_path=dump)
    with Pipeline(cfg, sinks=[]) as pipe:
        stats = pipe.run()
    doc = TE.render(TE.load_events(dump))
    assert TE.validate(doc) == []
    evs = doc["traceEvents"]
    slices = [e for e in evs if e["ph"] == "X"]
    for stage in ("ingest", "dispatch", "fetch", "sink"):
        assert sum(1 for e in slices if e["name"] == stage) \
            == stats.segments
    # flow chains: one per segment, start on the engine thread's
    # track, finish (bp=e) on the sink thread's track
    starts = [e for e in evs if e["ph"] == "s"]
    finishes = [e for e in evs if e["ph"] == "f"]
    assert len(starts) == len(finishes) == stats.segments
    assert {e["id"] for e in starts} == {e["id"] for e in finishes}
    for s, f in zip(sorted(starts, key=lambda e: e["id"]),
                    sorted(finishes, key=lambda e: e["id"])):
        assert s["tid"] != f["tid"]  # crosses the thread boundary
        assert f["bp"] == "e"
    # CLI: validate mode + file output
    assert TE.main([dump, "--validate"]) == 0
    out = str(tmp_path / "t.json")
    assert TE.main([dump, "--out", out]) == 0
    assert TE.validate(json.load(open(out))) == []


def test_trace_export_one_lane_per_stream(tmp_path):
    """Multi-stream dumps render one trace *process* per stream (the
    fleet view: lanes side by side)."""
    from srtb_tpu.tools import trace_export as TE

    path = str(tmp_path / "ev.jsonl")
    with open(path, "w") as f:
        t = 100.0
        for stream in ("beam0", "beam1"):
            for i, stage in enumerate(("stage.ingest",
                                       "stage.dispatch",
                                       "stage.fetch", "stage.sink")):
                t += 0.001
                f.write(json.dumps({
                    "t": t, "ts": t, "type": stage,
                    "trace": 1 if stream == "beam0" else 2,
                    "stream": stream, "seg": 0, "dur_ms": 0.5,
                    "info": "",
                    "thread": "main" if i < 3 else "sink"}) + "\n")
        f.write(json.dumps({
            "t": t + 1, "ts": t + 1, "type": "heal.demote",
            "trace": 2, "stream": "beam1", "seg": 0, "dur_ms": 0,
            "info": "staged@1", "thread": "main"}) + "\n")
    doc = TE.render(TE.load_events(path))
    assert TE.validate(doc) == []
    procs = {e["args"]["name"] for e in doc["traceEvents"]
             if e.get("name") == "process_name"}
    assert procs == {"stream:beam0", "stream:beam1"}
    assert doc["otherData"]["streams"] == ["beam0", "beam1"]
    # decisions render as thread-scoped instants
    inst = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert len(inst) == 1 and inst[0]["name"] == "heal.demote"


def test_trace_export_rejects_garbage(tmp_path):
    from srtb_tpu.tools import trace_export as TE

    empty = tmp_path / "empty.jsonl"
    empty.write_text("not json\n")
    assert TE.main([str(empty), "--validate"]) == 1
    assert TE.validate({"traceEvents": "nope"}) != []
    assert TE.validate({"traceEvents": [
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0}]}) != []  # no dur
    assert TE.validate({"traceEvents": [
        {"ph": "s", "pid": 1, "tid": 1, "ts": 0.0, "id": 1}]}) != []


# --------------------------------------------------------------- SLO/burn


def _clocked_tracker(**kw):
    t = [0.0]

    def clock():
        return t[0]

    kw.setdefault("fast_window_s", 10.0)
    kw.setdefault("slow_window_s", 100.0)
    return SloTracker(clock=clock, **kw), t


def test_slo_latency_burn_states():
    tr, t = _clocked_tracker(latency_ms=10.0, latency_budget=0.1)
    assert tr.objectives == ("latency",)
    # 100 good segments: ok, burn 0
    for _ in range(100):
        t[0] += 0.1
        tr.note_segment("", 0.005)
    rep = tr.evaluate()["_pipeline"]["latency"]
    assert rep == {"burn_fast": 0.0, "burn_slow": 0.0, "state": "ok"}
    # 5% bad < 10% budget: degraded, burn 0.5
    for i in range(100):
        t[0] += 0.01
        tr.note_segment("", 0.05 if i % 20 == 0 else 0.005)
    rep = tr.evaluate()["_pipeline"]["latency"]
    assert rep["state"] == "degraded"
    assert 0.0 < rep["burn_fast"] < 1.0
    # sustained 100% bad: burning on both windows
    for _ in range(300):
        t[0] += 0.5
        tr.note_segment("", 0.05)
    rep = tr.evaluate()["_pipeline"]["latency"]
    assert rep["state"] == "burning"
    assert rep["burn_fast"] >= 1.0 and rep["burn_slow"] >= 1.0
    # gauges landed (flat stream -> no stream label)
    assert metrics.get("slo_state",
                       labels={"objective": "latency"}) == 2
    assert metrics.get(
        "slo_burn_rate",
        labels={"objective": "latency", "window": "fast"}) >= 1.0


def test_slo_loss_burn_per_stream():
    tr, t = _clocked_tracker(loss_budget=0.01)
    for _ in range(99):
        t[0] += 0.01
        tr.note_segment("beamA", 0.001)
        tr.note_segment("beamB", 0.001)
    tr.note_dropped("beamB", 99)  # 50% loss on B only
    rep = tr.evaluate()
    assert rep["beamA"]["loss"]["state"] == "ok"
    assert rep["beamB"]["loss"]["state"] == "burning"
    assert rep["beamA"]["ok"] and not rep["beamB"]["ok"]
    assert metrics.get("slo_state", labels={
        "objective": "loss", "stream": "beamB"}) == 2
    assert metrics.get("slo_state", labels={
        "objective": "loss", "stream": "beamA"}) == 0


def test_slo_staleness_burn():
    tr, t = _clocked_tracker(staleness_s=5.0, staleness_budget=0.1)
    tr.note_segment("", 0.001)
    t[0] += 4.0  # within the allowed gap
    assert tr.evaluate()["_pipeline"]["staleness"]["state"] == "ok"
    t[0] += 12.0  # 11 s beyond: > 10% of both windows
    rep = tr.evaluate()["_pipeline"]["staleness"]
    assert rep["state"] == "burning" and rep["burn_fast"] > 1.0


def test_slo_state_transition_emits_event():
    tr, t = _clocked_tracker(loss_budget=0.01)
    tr.note_segment("", 0.001)
    tr.evaluate()
    tr.note_dropped("", 10)
    tr.evaluate()
    evs = [e for e in events.hub.dump() if e["type"] == "slo"]
    assert evs and evs[-1]["info"] == "loss:ok->burning"


def test_healthz_carries_slo_section(tmp_path):
    cfg = Config(slo_latency_ms=50.0, slo_loss_budget=0.01)
    tracker = slo.configure(cfg)
    assert tracker is not None and slo.tracker is tracker
    slo.note_segment("", 0.001)
    telemetry.mark_segment()
    h = telemetry.health(stale_after_s=30.0)
    assert h["ok"] and h["slo_ok"]
    assert set(h["slo"]["_pipeline"]) == {"latency", "loss", "ok"}
    # a second configure with identical params keeps the tracker (a
    # fleet's lanes share it)
    assert slo.configure(cfg) is tracker
    # an unarmed config does NOT disarm a live tracker
    assert slo.configure(Config()) is tracker


def test_pipeline_feeds_slo(tmp_path):
    from srtb_tpu.pipeline.runtime import Pipeline

    cfg = _mk_cfg(tmp_path, "slo", slo_latency_ms=1e9,
                  slo_loss_budget=0.5)
    with Pipeline(cfg, sinks=[]) as pipe:
        stats = pipe.run()
    rep = slo.evaluate()
    assert rep is not None
    per = rep["_pipeline"]
    assert per["latency"]["state"] == "ok"
    assert per["loss"]["state"] == "ok"
    assert per["ok"]
    # the latency denominator saw every drained segment
    st = slo.tracker._streams[""]
    assert st.lat[0].total() == stats.segments
