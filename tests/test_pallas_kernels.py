"""Pallas kernel tests in interpret mode (CPU), validated against the jnp
reference ops — the same generic-vs-handwritten self-consistency strategy
as the reference's unpack tests."""

import jax.numpy as jnp
import numpy as np
import pytest

from srtb_tpu.ops import dedisperse as dd
from srtb_tpu.ops import pallas_kernels as pk
from srtb_tpu.ops import unpack as U


def test_dedisperse_df64_kernel_matches_host_chirp():
    n = 1 << 15
    f_min, bw, dm = 1405.0, 64.0, 150.0
    f_c = f_min + bw
    df = bw / n
    rng = np.random.default_rng(0)
    spec = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(
        np.complex64)
    spec_ri = jnp.stack([jnp.asarray(spec.real), jnp.asarray(spec.imag)])

    out_ri = np.asarray(pk.dedisperse_df64(spec_ri, f_min, df, f_c, dm,
                                           interpret=True))
    got = out_ri[0] + 1j * out_ri[1]
    expected = spec * dd.chirp_factor_host(n, f_min, df, f_c, dm)
    # df64 phase error ~1e-5 turns; compare phasors
    err = np.abs(got - expected)
    assert np.max(err) < 5e-3 * np.max(np.abs(spec))


def test_dedisperse_df64_kernel_high_dm():
    """|k| ~ 1e9 regime (J1644-style high DM)."""
    n = 1 << 12
    f_min, bw, dm = 1437.0, -64.0, -478.80
    f_c = f_min + bw
    df = bw / n
    spec = np.ones(n, dtype=np.complex64)
    spec_ri = jnp.stack([jnp.ones(n, jnp.float32), jnp.zeros(n, jnp.float32)])
    out_ri = np.asarray(pk.dedisperse_df64(spec_ri, f_min, df, f_c, dm,
                                           interpret=True))
    got = out_ri[0] + 1j * out_ri[1]
    expected = np.asarray(dd.chirp_factor_host(n, f_min, df, f_c, dm))
    # unit-magnitude phasors with df64-level phase accuracy
    np.testing.assert_allclose(np.abs(got), 1.0, atol=1e-5)
    phase_err = np.abs(np.angle(got * np.conj(expected)))
    assert np.percentile(phase_err, 99) < 2e-2
    del spec


@pytest.mark.parametrize("with_window", [False, True])
def test_unpack_2bit_kernel(with_window):
    rng = np.random.default_rng(1)
    m = 1 << 12
    data = rng.integers(0, 256, size=m, dtype=np.uint8)
    window = (rng.random(4 * m).astype(np.float32) + 0.5
              if with_window else None)
    got = np.asarray(pk.unpack_2bit_window(
        jnp.asarray(data),
        None if window is None else jnp.asarray(window),
        interpret=True))
    expected = U.unpack_oracle(data, 2)
    if window is not None:
        expected = expected * window
    np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_sk_zap_timeseries_matches_jnp():
    from srtb_tpu.ops import detect as det
    from srtb_tpu.ops import rfi

    nfreq, ntime = 32, 1024
    rng = np.random.default_rng(5)
    wf = (rng.standard_normal((nfreq, ntime))
          + 1j * rng.standard_normal((nfreq, ntime))).astype(np.complex64)
    # make some rows RFI-like so SK zaps them, and one row exactly zero
    wf[3] *= np.exp(1j * 0.1) * (1 + 10 * (rng.random(ntime) < 0.01))
    wf[7] = 0.0
    wf[12] *= 5.0 * np.sin(np.arange(ntime) * 0.3) ** 2

    sk_threshold = 1.05
    wf_ri = jnp.stack([jnp.asarray(wf.real), jnp.asarray(wf.imag)])
    out_ri, zero_count, ts = pk.sk_zap_timeseries(wf_ri, sk_threshold,
                                                  interpret=True)

    expected_wf = rfi.mitigate_rfi_spectral_kurtosis(
        jnp.asarray(wf)[None], sk_threshold)[0]
    got_wf = np.asarray(out_ri[0]) + 1j * np.asarray(out_ri[1])
    np.testing.assert_allclose(got_wf, np.asarray(expected_wf),
                               rtol=1e-5, atol=1e-5)
    # some but not all rows must be zapped for the test to mean anything
    zapped_rows = int((np.abs(np.asarray(expected_wf)).sum(-1) == 0).sum())
    assert 0 < zapped_rows < nfreq

    expected_det = det.detect(expected_wf[None], 0, 8.0, 64)
    assert int(zero_count) == int(expected_det.zero_count[0])
    expected_ts_raw = np.abs(np.asarray(expected_wf)) ** 2
    np.testing.assert_allclose(np.asarray(ts),
                               expected_ts_raw.sum(axis=0),
                               rtol=1e-4, atol=1e-4)

    # chained through the split-out ladder: full DetectResult parity
    got_det = det.detect_from_time_series(
        jnp.asarray(ts)[None], jnp.asarray([zero_count]), 8.0, 64)
    np.testing.assert_allclose(np.asarray(got_det.time_series),
                               np.asarray(expected_det.time_series),
                               rtol=1e-4, atol=1e-4)
    assert np.array_equal(np.asarray(got_det.signal_counts),
                          np.asarray(expected_det.signal_counts))


@pytest.mark.parametrize("nbits", [1, 2, 4])
def test_unpack_subbyte_kernel_all_widths(nbits):
    m = 1 << 10
    rng = np.random.default_rng(nbits)
    raw = rng.integers(0, 256, size=m, dtype=np.uint8)
    n_out = (8 // nbits) * m
    win = np.hamming(n_out).astype(np.float32)
    got = np.asarray(pk.unpack_subbyte_window(
        jnp.asarray(raw), nbits, jnp.asarray(win), interpret=True))
    expected = np.asarray(U.unpack(jnp.asarray(raw), nbits,
                                   jnp.asarray(win)))
    np.testing.assert_allclose(got, expected, rtol=1e-6, atol=1e-6)


def test_dedisperse_df64_kernel_high_channel_offset():
    """The in-kernel chirp must stay phase-accurate when the global
    channel index exceeds float32's exact-integer range (2^24)."""
    n = 1 << 12
    i0 = (1 << 26) + 1024
    n_spec = 1 << 27
    f_min, bw, dm = 1405.0 + 32.0, -64.0, -478.80
    f_c = f_min + bw
    df = bw / n_spec
    rng = np.random.default_rng(1)
    spec = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(
        np.complex64)
    spec_ri = jnp.stack([jnp.asarray(spec.real), jnp.asarray(spec.imag)])
    out_ri = np.asarray(pk.dedisperse_df64(spec_ri, f_min, df, f_c, dm,
                                           interpret=True, i0=i0))
    got = out_ri[0] + 1j * out_ri[1]

    i = np.arange(i0, i0 + n, dtype=np.float64)
    f = f_min + df * i
    delta_f = f - f_c
    k = (dd.D * 1e6) * dm / f * (delta_f / f_c) ** 2
    chirp = np.exp(-2j * np.pi * np.modf(k)[0]).astype(np.complex64)
    err = np.abs(got - spec * chirp)
    assert err.max() < 5e-3 * np.abs(spec).max(), err.max()
