"""Pallas kernel tests in interpret mode (CPU), validated against the jnp
reference ops — the same generic-vs-handwritten self-consistency strategy
as the reference's unpack tests."""

import jax.numpy as jnp
import numpy as np
import pytest

from srtb_tpu.ops import dedisperse as dd
from srtb_tpu.ops import pallas_kernels as pk
from srtb_tpu.ops import unpack as U


def test_dedisperse_df64_kernel_matches_host_chirp():
    n = 1 << 15
    f_min, bw, dm = 1405.0, 64.0, 150.0
    f_c = f_min + bw
    df = bw / n
    rng = np.random.default_rng(0)
    spec = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(
        np.complex64)
    spec_ri = jnp.stack([jnp.asarray(spec.real), jnp.asarray(spec.imag)])

    out_ri = np.asarray(pk.dedisperse_df64(spec_ri, f_min, df, f_c, dm,
                                           interpret=True))
    got = out_ri[0] + 1j * out_ri[1]
    expected = spec * dd.chirp_factor_host(n, f_min, df, f_c, dm)
    # df64 phase error ~1e-5 turns; compare phasors
    err = np.abs(got - expected)
    assert np.max(err) < 5e-3 * np.max(np.abs(spec))


def test_dedisperse_df64_kernel_high_dm():
    """|k| ~ 1e9 regime (J1644-style high DM)."""
    n = 1 << 12
    f_min, bw, dm = 1437.0, -64.0, -478.80
    f_c = f_min + bw
    df = bw / n
    spec = np.ones(n, dtype=np.complex64)
    spec_ri = jnp.stack([jnp.ones(n, jnp.float32), jnp.zeros(n, jnp.float32)])
    out_ri = np.asarray(pk.dedisperse_df64(spec_ri, f_min, df, f_c, dm,
                                           interpret=True))
    got = out_ri[0] + 1j * out_ri[1]
    expected = np.asarray(dd.chirp_factor_host(n, f_min, df, f_c, dm))
    # unit-magnitude phasors with df64-level phase accuracy
    np.testing.assert_allclose(np.abs(got), 1.0, atol=1e-5)
    phase_err = np.abs(np.angle(got * np.conj(expected)))
    assert np.percentile(phase_err, 99) < 2e-2
    del spec


@pytest.mark.parametrize("with_window", [False, True])
def test_unpack_2bit_kernel(with_window):
    rng = np.random.default_rng(1)
    m = 1 << 12
    data = rng.integers(0, 256, size=m, dtype=np.uint8)
    window = (rng.random(4 * m).astype(np.float32) + 0.5
              if with_window else None)
    got = np.asarray(pk.unpack_2bit_window(
        jnp.asarray(data),
        None if window is None else jnp.asarray(window),
        interpret=True))
    expected = U.unpack_oracle(data, 2)
    if window is not None:
        expected = expected * window
    np.testing.assert_allclose(got, expected, rtol=1e-6)
