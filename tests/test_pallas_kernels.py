"""Pallas kernel tests validated against the jnp reference ops — the same
generic-vs-handwritten self-consistency strategy as the reference's unpack
tests.

Every case runs in interpret mode (CPU CI) and, when a real TPU is
present and ``SRTB_TEST_TPU=1`` (see conftest), again non-interpret so
the Mosaic lowering itself is exercised — interpret mode routinely
accepts kernels Mosaic rejects (layouts, unsupported primitives)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srtb_tpu.ops import dedisperse as dd
from srtb_tpu.ops import pallas_kernels as pk
from srtb_tpu.ops import unpack as U


@pytest.fixture(params=["interpret", "mosaic"])
def interpret(request):
    if request.param == "mosaic":
        if not (os.environ.get("SRTB_TEST_TPU")
                and jax.default_backend() == "tpu"):
            pytest.skip("real TPU run needs SRTB_TEST_TPU=1 and a chip")
        return False
    return True


def test_dedisperse_df64_kernel_matches_host_chirp(interpret):
    n = 1 << 15
    f_min, bw, dm = 1405.0, 64.0, 150.0
    f_c = f_min + bw
    df = bw / n
    rng = np.random.default_rng(0)
    spec = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(
        np.complex64)
    spec_ri = jnp.stack([jnp.asarray(spec.real), jnp.asarray(spec.imag)])

    out_ri = np.asarray(pk.dedisperse_df64(spec_ri, f_min, df, f_c, dm,
                                           interpret=interpret))
    got = out_ri[0] + 1j * out_ri[1]
    expected = spec * dd.chirp_factor_host(n, f_min, df, f_c, dm)
    # df64 phase error ~1e-5 turns; compare phasors
    err = np.abs(got - expected)
    assert np.max(err) < 5e-3 * np.max(np.abs(spec))


def test_dedisperse_df64_kernel_high_dm(interpret):
    """|k| ~ 1e9 regime (J1644-style high DM)."""
    n = 1 << 12
    f_min, bw, dm = 1437.0, -64.0, -478.80
    f_c = f_min + bw
    df = bw / n
    spec = np.ones(n, dtype=np.complex64)
    spec_ri = jnp.stack([jnp.ones(n, jnp.float32), jnp.zeros(n, jnp.float32)])
    out_ri = np.asarray(pk.dedisperse_df64(spec_ri, f_min, df, f_c, dm,
                                           interpret=interpret))
    got = out_ri[0] + 1j * out_ri[1]
    expected = np.asarray(dd.chirp_factor_host(n, f_min, df, f_c, dm))
    # unit-magnitude phasors with df64-level phase accuracy
    np.testing.assert_allclose(np.abs(got), 1.0, atol=1e-5)
    phase_err = np.abs(np.angle(got * np.conj(expected)))
    assert np.percentile(phase_err, 99) < 2e-2
    del spec


def _xfail_unpack_mosaic(interpret):
    if not interpret and not pk.UNPACK_MOSAIC_OK:
        pytest.xfail("sub-byte lane interleave not lowerable by Mosaic "
                     "(infer-vector-layout: unsupported shape cast); "
                     "real-TPU segments use the XLA unpack instead")


@pytest.mark.parametrize("with_window", [False, True])
def test_unpack_2bit_kernel(with_window, interpret):
    _xfail_unpack_mosaic(interpret)
    rng = np.random.default_rng(1)
    m = 1 << 12
    data = rng.integers(0, 256, size=m, dtype=np.uint8)
    window = (rng.random(4 * m).astype(np.float32) + 0.5
              if with_window else None)
    got = np.asarray(pk.unpack_2bit_window(
        jnp.asarray(data),
        None if window is None else jnp.asarray(window),
        interpret=interpret))
    expected = U.unpack_oracle(data, 2)
    if window is not None:
        expected = expected * window
    np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_sk_zap_timeseries_matches_jnp(interpret):
    """Fused SK kernel vs an independent float64 numpy oracle.

    Deliberately no complex device arrays: some TPU runtimes (the axon
    tunnel) cannot transfer complex64 host<->device, and one failed
    complex transfer poisons every later transfer in the process — the
    kernel's own boundary is (re, im) f32, so the test honors it too.
    Threshold 1.2 keeps every row's SK decision >= 0.1 from a boundary
    (at 1.05 a clean row sat 1.4e-4 from the cut: f32-reorder flaky).
    """
    from srtb_tpu.ops import detect as det
    from srtb_tpu.ops import rfi

    nfreq, ntime = 32, 1024
    rng = np.random.default_rng(5)
    wf = (rng.standard_normal((nfreq, ntime))
          + 1j * rng.standard_normal((nfreq, ntime))).astype(np.complex64)
    # make some rows RFI-like so SK zaps them, and one row exactly zero
    wf[3] *= np.exp(1j * 0.1) * (1 + 10 * (rng.random(ntime) < 0.01))
    wf[7] = 0.0
    wf[12] *= 5.0 * np.sin(np.arange(ntime) * 0.3) ** 2

    sk_threshold = 1.2
    wf_ri = jnp.stack([jnp.asarray(wf.real.copy()),
                       jnp.asarray(wf.imag.copy())])
    out_ri, zero_count, ts = pk.sk_zap_timeseries(wf_ri, sk_threshold,
                                                  interpret=interpret)

    # float64 oracle of the SK decision (formula:
    # spectrum/rfi_mitigation.hpp:290-341, thresholds shared via
    # sk_decision_thresholds so the decision rule cannot drift)
    x2 = np.abs(wf.astype(np.complex128)) ** 2
    s2 = x2.sum(-1)
    s4 = (x2 * x2).sum(-1)
    with np.errstate(invalid="ignore"):
        sk = ntime * s4 / (s2 * s2)
    thr_low, thr_high = rfi.sk_decision_thresholds(ntime, sk_threshold)
    zap = (sk > thr_high) | (sk < thr_low)
    margin = np.nanmin(np.minimum(np.abs(sk - thr_low),
                                  np.abs(sk - thr_high)))
    assert margin > 0.05, f"borderline SK row (margin {margin})"
    expected_wf = np.where(zap[:, None], 0, wf).astype(np.complex64)
    # some but not all rows must be zapped for the test to mean anything
    assert 0 < int(zap.sum()) < nfreq

    got_wf = np.asarray(out_ri[0]) + 1j * np.asarray(out_ri[1])
    np.testing.assert_allclose(got_wf, expected_wf, rtol=1e-5, atol=1e-5)

    expected_zero = int((zap | (x2[:, 0] == 0)).sum())
    assert int(zero_count) == expected_zero
    expected_ts = np.abs(expected_wf) ** 2
    np.testing.assert_allclose(np.asarray(ts), expected_ts.sum(axis=0),
                               rtol=1e-4, atol=1e-4)

    # chained through the split-out ladder: DetectResult consistency on
    # real-only inputs (no complex crosses the device boundary)
    got_det = det.detect_from_time_series(
        jnp.asarray(ts)[None], jnp.asarray([zero_count]), 8.0, 64)
    ref_det = det.detect_from_time_series(
        jnp.asarray(expected_ts.sum(axis=0).astype(np.float32))[None],
        jnp.asarray([expected_zero]), 8.0, 64)
    np.testing.assert_allclose(np.asarray(got_det.time_series),
                               np.asarray(ref_det.time_series),
                               rtol=1e-4, atol=1e-4)
    assert np.array_equal(np.asarray(got_det.signal_counts),
                          np.asarray(ref_det.signal_counts))


@pytest.mark.parametrize("nbits", [1, 2, 4])
def test_unpack_subbyte_kernel_all_widths(nbits, interpret):
    _xfail_unpack_mosaic(interpret)
    m = 1 << 10
    rng = np.random.default_rng(nbits)
    raw = rng.integers(0, 256, size=m, dtype=np.uint8)
    n_out = (8 // nbits) * m
    win = np.hamming(n_out).astype(np.float32)
    got = np.asarray(pk.unpack_subbyte_window(
        jnp.asarray(raw), nbits, jnp.asarray(win), interpret=interpret))
    expected = np.asarray(U.unpack(jnp.asarray(raw), nbits,
                                   jnp.asarray(win)))
    np.testing.assert_allclose(got, expected, rtol=1e-6, atol=1e-6)


def test_dedisperse_df64_kernel_high_channel_offset(interpret):
    """The in-kernel chirp must stay phase-accurate when the global
    channel index exceeds float32's exact-integer range (2^24)."""
    n = 1 << 12
    i0 = (1 << 26) + 1024
    n_spec = 1 << 27
    f_min, bw, dm = 1405.0 + 32.0, -64.0, -478.80
    f_c = f_min + bw
    df = bw / n_spec
    rng = np.random.default_rng(1)
    spec = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(
        np.complex64)
    spec_ri = jnp.stack([jnp.asarray(spec.real), jnp.asarray(spec.imag)])
    out_ri = np.asarray(pk.dedisperse_df64(spec_ri, f_min, df, f_c, dm,
                                           interpret=interpret, i0=i0))
    got = out_ri[0] + 1j * out_ri[1]

    i = np.arange(i0, i0 + n, dtype=np.float64)
    f = f_min + df * i
    delta_f = f - f_c
    k = (dd.D * 1e6) * dm / f * (delta_f / f_c) ** 2
    chirp = np.exp(-2j * np.pi * np.modf(k)[0]).astype(np.complex64)
    err = np.abs(got - spec * chirp)
    assert err.max() < 5e-3 * np.abs(spec).max(), err.max()


@pytest.mark.parametrize("with_mask", [False, True])
def test_rfi_s1_dedisperse_fused_matches_jnp_sequence(interpret, with_mask):
    """The fused RFI-s1 + chirp kernel must reproduce the jnp sequence
    mitigate_rfi_average_and_normalize -> mitigate_rfi_manual -> chirp
    multiply (ref: rfi_mitigation_pipe.hpp:50-94 + dedisperse_pipe)."""
    from srtb_tpu.ops import rfi

    n = 1 << 15
    f_min, bw, dm = 1405.0, 64.0, 150.0
    f_c = f_min + bw
    df = bw / n
    threshold, norm = 1.8, 0.125
    rng = np.random.default_rng(7)
    spec = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(
        np.complex64)
    spec[100] *= 30.0  # guarantee at least one zapped channel
    mask = None
    if with_mask:  # zap mask: True = zero the bin (rfi.rfi_ranges_to_mask)
        mask_np = np.zeros(n, bool)
        mask_np[2048:4096] = True
        mask = jnp.asarray(mask_np)
    spec_ri = jnp.stack([jnp.asarray(spec.real), jnp.asarray(spec.imag)])

    out_ri = np.asarray(pk.rfi_s1_dedisperse_df64(
        spec_ri, threshold, norm, f_min, df, f_c, dm, mask=mask,
        interpret=interpret))
    got = out_ri[0] + 1j * out_ri[1]

    want = rfi.mitigate_rfi_average_and_normalize(
        jnp.asarray(spec)[None, :], threshold, norm)
    want = rfi.mitigate_rfi_manual(want, mask)[0]
    want = np.asarray(want) * dd.chirp_factor_host(n, f_min, df, f_c, dm)
    assert np.max(np.abs(got - want)) < 5e-3 * np.max(np.abs(want))


@pytest.mark.parametrize("nbits", [1, 2, 4])
def test_unpack_planes_kernel_matches_jnp(nbits):
    """Blocked-plane Pallas unpack (the Mosaic-lowerable spelling) vs the
    XLA unpack_subbyte_planes, with and without the blocked window."""
    from srtb_tpu.ops import fft as F
    from srtb_tpu.ops import unpack as U

    rng = np.random.default_rng(3)
    m = 1 << 11
    data = jnp.asarray(rng.integers(0, 256, m, dtype=np.uint8))
    want = np.asarray(U.unpack_subbyte_planes(data, nbits))
    got = np.asarray(pk.unpack_subbyte_planes_window(data, nbits,
                                                     interpret=True))
    np.testing.assert_array_equal(got, want)
    win = F.subbyte_window_planes(
        (np.hanning((8 // nbits) * m) + 0.1).astype(np.float32), nbits)
    got_w = np.asarray(pk.unpack_subbyte_planes_window(
        data, nbits, jnp.asarray(win), interpret=True))
    np.testing.assert_allclose(got_w, want * win, rtol=1e-6)


def test_blocked_pipeline_uses_planes_unpack(monkeypatch):
    """use_pallas on the blocked sub-byte path must route through the
    fused planes-unpack kernel (interpret mode) and produce the same
    waterfall as the XLA unpack."""
    from srtb_tpu.config import Config
    from srtb_tpu.pipeline.segment import SegmentProcessor, \
        waterfall_to_numpy

    cfg = Config(
        baseband_input_count=1 << 14,
        baseband_input_bits=2,
        baseband_format_type="simple",
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        dm=30.0,
        spectrum_channel_count=1 << 5,
        mitigate_rfi_average_method_threshold=1e9,
        mitigate_rfi_spectral_kurtosis_threshold=1e9,
        baseband_reserve_sample=False,
        fft_strategy="four_step",
    )
    rng = np.random.default_rng(4)
    raw = rng.integers(0, 256, cfg.segment_bytes(1), dtype=np.uint8)
    base = waterfall_to_numpy(SegmentProcessor(cfg).process(raw)[0])

    called = []
    orig = pk.unpack_subbyte_planes_window

    def spy(*a, **kw):
        called.append(True)
        return orig(*a, **kw)

    monkeypatch.setattr(pk, "unpack_subbyte_planes_window", spy)
    wf = waterfall_to_numpy(
        SegmentProcessor(cfg.replace(use_pallas=True)).process(raw)[0])
    assert called, "planes unpack kernel was not used"
    np.testing.assert_allclose(wf, base, rtol=2e-3, atol=1e-4)


def test_pallas_chirp_exact_fallback_path(monkeypatch):
    """The exact per-element in-kernel chirp (the anchored rewrite's
    fallback, forced via SRTB_PALLAS_CHIRP_EXACT=1) must still match the
    f64 host chirp — a regression here would ship silently since every
    physical config otherwise takes the anchored path."""
    from srtb_tpu.ops import dedisperse as dd

    monkeypatch.setenv("SRTB_PALLAS_CHIRP_EXACT", "1")
    n = 1 << 12
    f_min, bw, dm = 1405.0 + 32.0, -64.0, -478.80
    f_c = f_min + bw
    df = bw / (1 << 22)  # flagship-scale df; i0=0 slice of it
    rng = np.random.default_rng(5)
    spec = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(
        np.complex64)
    spec_ri = jnp.stack([jnp.asarray(spec.real), jnp.asarray(spec.imag)])
    assert pk._chirp_consts(n, f_min, df, f_c, dm, 0) is None  # knob works
    out_ri = np.asarray(pk.dedisperse_df64(spec_ri, f_min, df, f_c, dm,
                                           interpret=True))
    got = out_ri[0] + 1j * out_ri[1]
    host = dd.chirp_factor_host(n, f_min, df, f_c, dm)
    err = np.abs(got - spec * host)
    assert err.max() < 5e-3 * np.abs(spec).max(), err.max()


def test_planes_tiling_ok_gates_fallback():
    assert pk.planes_tiling_ok(128 * 256)
    assert not pk.planes_tiling_ok(64)        # not a multiple of 128
    assert not pk.planes_tiling_ok(128 * 384)  # rows not divisible
    # small segments: rows_total < _ROWS uses rows_total itself
    assert pk.planes_tiling_ok(128 * 8)
