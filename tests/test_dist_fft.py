"""Distributed FFT tests on the virtual 8-device CPU mesh
(multi-chip logic tested the way the reference tests multi-backend code on
CPU-only CI, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srtb_tpu.parallel import dist_fft as DF
from srtb_tpu.parallel import mesh as M


@pytest.fixture(scope="module")
def seq_mesh8():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    return M.seq_mesh(8)


@pytest.mark.parametrize("log2n", [10, 14, 16])
@pytest.mark.parametrize("inverse", [False, True])
def test_dist_fft_matches_numpy(seq_mesh8, log2n, inverse):
    n = 1 << log2n
    rng = np.random.default_rng(log2n)
    x = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(
        np.complex64)
    got = np.asarray(DF.dist_fft(jnp.asarray(x), seq_mesh8,
                                 inverse=inverse))
    expected = np.fft.ifft(x) * n if inverse else np.fft.fft(x)
    np.testing.assert_allclose(got, expected.astype(np.complex64),
                               rtol=1e-3, atol=3e-2 * np.sqrt(n))


@pytest.mark.parametrize("log2n", [12, 16])
def test_dist_rfft(seq_mesh8, log2n):
    n = 1 << log2n
    rng = np.random.default_rng(log2n)
    x = rng.standard_normal(n).astype(np.float32)
    got = np.asarray(DF.dist_rfft_drop_nyquist(jnp.asarray(x), seq_mesh8))
    expected = np.fft.rfft(x)[:-1]
    assert got.shape == (n // 2,)
    np.testing.assert_allclose(got, expected.astype(np.complex64),
                               rtol=1e-3, atol=3e-2 * np.sqrt(n))


@pytest.mark.slow  # 2^24 on the CPU mesh: ~10-15 s each
def test_dist_fft_large_n_twiddle_precision(seq_mesh8):
    """At n >= 2^24 a twiddle phase computed as a plain f32 ratio product
    loses enough mantissa to corrupt whole bins; the hi/lo integer-split
    phase (ops/fft.py:_phase_exp) must hold relative RMS error near f32
    roundoff against a float64 oracle."""
    n = 1 << 24
    rng = np.random.default_rng(24)
    x64 = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    x = x64.astype(np.complex64)
    got = np.asarray(DF.dist_fft(jnp.asarray(x), seq_mesh8))
    expected = np.fft.fft(x64)  # float64 oracle
    rel_rms = (np.linalg.norm(got - expected)
               / np.linalg.norm(expected))
    # exact twiddles leave only local-FFT f32 roundoff (~1e-6 * sqrt(log n));
    # the old f32 ratio-product twiddle fails this by orders of magnitude
    assert rel_rms < 5e-6, f"rel RMS {rel_rms:.2e}"


@pytest.mark.slow  # 2^24 on the CPU mesh: ~10-15 s each
def test_dist_rfft_large_n_twiddle_precision(seq_mesh8):
    """Same large-n precision discipline for the Hermitian post-process
    twiddle exp(-i*pi*k/m) of the distributed R2C."""
    n = 1 << 24
    rng = np.random.default_rng(42)
    x64 = rng.standard_normal(n)
    x = x64.astype(np.float32)
    got = np.asarray(DF.dist_rfft_drop_nyquist(jnp.asarray(x), seq_mesh8))
    expected = np.fft.rfft(x64)[:-1]  # float64 oracle
    rel_rms = (np.linalg.norm(got - expected)
               / np.linalg.norm(expected))
    assert rel_rms < 5e-6, f"rel RMS {rel_rms:.2e}"


def test_dist_fft_output_sharding(seq_mesh8):
    n = 1 << 12
    x = jnp.asarray(np.random.default_rng(0).standard_normal(n)
                    .astype(np.float32)).astype(jnp.complex64)
    out = DF.dist_fft(x, seq_mesh8)
    # output stays sharded over the seq axis (no implicit gather)
    assert len(out.sharding.device_set) == 8


@pytest.mark.slow  # 2^24 on the CPU mesh: ~10-15 s each
def test_dist_fft_pallas_legs(seq_mesh8):
    """Pallas VMEM leg FFTs under the a2a transposes (rows_impl knob):
    local legs at n = 2^24 are [2048, 4096]-shaped — inside the row
    kernel's window, so the kernel really fires on every device — and
    the distributed result must match numpy like the XLA legs do."""
    n = 1 << 24
    rng = np.random.default_rng(42)
    x = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(
        np.complex64)
    got = np.asarray(DF.dist_fft(jnp.asarray(x), seq_mesh8,
                                 rows_impl="pallas_interpret"))
    want = np.fft.fft(x.astype(np.complex128))
    scale = np.abs(want).max()
    assert np.abs(got - want).max() / scale < 2e-5


@pytest.mark.slow  # 2^24 on the CPU mesh: ~10-15 s each
def test_dist_rfft_pallas_legs_matches_xla_legs(seq_mesh8):
    """The full distributed R2C (pack + dist C2C + Hermitian mirror)
    must be leg-implementation-independent."""
    n = 1 << 24
    rng = np.random.default_rng(43)
    x = rng.standard_normal(n).astype(np.float32)
    base = np.asarray(DF.dist_rfft_drop_nyquist(jnp.asarray(x), seq_mesh8))
    got = np.asarray(DF.dist_rfft_drop_nyquist(
        jnp.asarray(x), seq_mesh8, rows_impl="pallas_interpret"))
    scale = np.abs(base).max()
    assert np.abs(got - base).max() / scale < 2e-5


def test_dist_fft_in_shard_four_step_recursion(seq_mesh8):
    """The 2^30+ production shapes make each in-shard leg longer than
    the XLA length cap, so the legs recurse into four_step_fft *inside*
    the shard_map body.  Force that branch at test scale by passing a
    low ``len_cap`` explicitly (round-4 verdict #7 de-globalized the
    cap): results must still match numpy."""
    n = 1 << 18   # legs 512 x 512, cap 256 -> every in-shard leg recurses
    rng = np.random.default_rng(5)
    x = (rng.standard_normal(n)
         + 1j * rng.standard_normal(n)).astype(np.complex64)
    got = np.asarray(DF.dist_fft(jnp.asarray(x), seq_mesh8,
                                 len_cap=1 << 8))
    want = np.fft.fft(x.astype(np.complex128))
    scale = np.abs(want).max()
    assert np.abs(got - want).max() / scale < 2e-5
