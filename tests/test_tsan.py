"""srtb-tsan: concurrency lint rules (lock-order-inversion,
blocking-under-lock, condvar-misuse, check-then-act) fixtures —
positive / negative / pragma / baseline per rule — plus the runtime
checker (analysis/tsan.py): live lockdep cycle trap, condvar wrapper
misuse traps, held-too-long stalls, claim-on-first-use ownership on a
fleet lane, the zero-cost-off contract, and the seeded schedule
perturber's determinism (same seed => same yield schedule => same
journal).
"""

import os
import re
import textwrap
import threading
import time

import pytest

from srtb_tpu.analysis import lint
from srtb_tpu.analysis.tsan import (InstrumentedCondition,
                                    InstrumentedLock,
                                    SchedulePerturber, Tsan, TsanError,
                                    install_perturber,
                                    uninstall_perturber)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(tmp_path, rel, src):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return str(p)


def _run(tmp_path):
    return lint.run([str(tmp_path)])


def _rules(findings):
    return [f.rule for f in findings]


# ------------------------------------------------ lock-order-inversion


class TestLockOrderInversion:
    def test_inverted_nesting_positive(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import threading

            class Engine:
                def __init__(self):
                    self.a_lock = threading.Lock()
                    self.b_lock = threading.Lock()

                def forward(self):
                    with self.a_lock:
                        with self.b_lock:
                            pass

                def backward(self):
                    with self.b_lock:
                        with self.a_lock:
                            pass
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["lock-order-inversion"]
        assert "cycle" in fs[0].message
        assert "a_lock" in fs[0].message and "b_lock" in fs[0].message

    def test_cross_function_positive(self, tmp_path):
        # one half of the cycle hides behind a call: forward holds A
        # and CALLS a helper that takes B
        _write(tmp_path, "mod.py", """
            import threading

            class Engine:
                def __init__(self):
                    self.a_lock = threading.Lock()
                    self.b_lock = threading.Lock()

                def _drain(self):
                    with self.b_lock:
                        pass

                def forward(self):
                    with self.a_lock:
                        self._drain()

                def backward(self):
                    with self.b_lock:
                        with self.a_lock:
                            pass
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["lock-order-inversion"]

    def test_reacquire_self_positive(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import threading

            class Engine:
                def __init__(self):
                    self.a_lock = threading.Lock()

                def step(self):
                    with self.a_lock:
                        with self.a_lock:
                            pass
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["lock-order-inversion"]
        assert "self-edge" in fs[0].message

    def test_consistent_order_negative(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import threading

            class Engine:
                def __init__(self):
                    self.a_lock = threading.Lock()
                    self.b_lock = threading.Lock()

                def forward(self):
                    with self.a_lock:
                        with self.b_lock:
                            pass

                def also_forward(self):
                    with self.a_lock:
                        with self.b_lock:
                            pass
        """)
        assert _run(tmp_path) == []

    def test_non_lock_with_negative(self, tmp_path):
        # open()/tempfile with-blocks never enter the order graph
        _write(tmp_path, "mod.py", """
            def save(path, other):
                with open(path) as f:
                    with open(other) as g:
                        return f.read() + g.read()
        """)
        assert _run(tmp_path) == []

    def test_pragma_suppresses(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import threading

            class Engine:
                def __init__(self):
                    self.a_lock = threading.Lock()
                    self.b_lock = threading.Lock()

                def forward(self):
                    with self.a_lock:
                        # srtb-lint: disable=lock-order-inversion
                        with self.b_lock:
                            pass

                def backward(self):
                    with self.b_lock:
                        with self.a_lock:
                            pass
        """)
        assert _run(tmp_path) == []


# ------------------------------------------------- blocking-under-lock


class TestBlockingUnderLock:
    def test_fdatasync_positive(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import os
            import threading

            class Wal:
                def __init__(self):
                    self._lock = threading.Lock()

                def flush(self, fd):
                    with self._lock:
                        os.fdatasync(fd)
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["blocking-under-lock"]
        assert "fdatasync" in fs[0].message

    def test_untimed_get_and_join_positive(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import threading

            class Sched:
                def __init__(self, q, pipe):
                    self._lock = threading.Lock()
                    self.q = q
                    self.sink_pipe = pipe

                def drain(self):
                    with self._lock:
                        item = self.q.get()
                        self.sink_pipe.join()
                        return item
        """)
        fs = _run(tmp_path)
        assert sorted(_rules(fs)) == ["blocking-under-lock"] * 2

    def test_foreign_wait_positive(self, tmp_path):
        # waiting on cv B while holding lock A deadlocks B's notifier
        # if it ever needs A; waiting on the cv you hold is sanctioned
        _write(tmp_path, "mod.py", """
            import threading

            class Sched:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cv = threading.Condition()

                def park(self):
                    with self._lock:
                        self._cv.wait(0.1)
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["blocking-under-lock"]
        assert "different lock" in fs[0].message

    def test_transitive_through_call_positive(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import os
            import threading

            class Wal:
                def __init__(self):
                    self._lock = threading.Lock()

                def _sync(self, fd):
                    os.fdatasync(fd)

                def commit(self, fd):
                    with self._lock:
                        self._sync(fd)
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["blocking-under-lock"]
        assert "_sync" in fs[0].message

    def test_negatives(self, tmp_path):
        # timed get, dict get, os.path.join, str.join, fsync outside
        # the lock: all quiet
        _write(tmp_path, "mod.py", """
            import os
            import threading

            class Sched:
                def __init__(self, q):
                    self._lock = threading.Lock()
                    self.q = q
                    self.d = {}

                def drain(self, fd):
                    with self._lock:
                        item = self.q.get(timeout=0.05)
                        name = self.d.get("key")
                        path = os.path.join("a", name or "b")
                        label = ",".join(["x", path])
                    os.fdatasync(fd)
                    return item, label
        """)
        assert _run(tmp_path) == []

    def test_pragma_suppresses(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import os
            import threading

            class Wal:
                def __init__(self):
                    self._lock = threading.Lock()

                def flush(self, fd):
                    with self._lock:
                        # WAL commit point is lock-serialized by design
                        # srtb-lint: disable=blocking-under-lock
                        os.fdatasync(fd)
        """)
        assert _run(tmp_path) == []


# ----------------------------------------------------- condvar-misuse


class TestCondvarMisuse:
    def test_wait_under_if_positive(self, tmp_path):
        # the fleet scheduler's pre-fix idle wait, reduced
        _write(tmp_path, "mod.py", """
            import threading

            class Sched:
                def __init__(self):
                    self._wake = threading.Condition()
                    self.seq = 0

                def idle(self, seen):
                    with self._wake:
                        if self.seq == seen:
                            self._wake.wait(0.05)
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["condvar-misuse"]
        assert "predicate loop" in fs[0].message

    def test_notify_without_lock_positive(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import threading

            class Sched:
                def __init__(self):
                    self._wake = threading.Condition()
                    self.seq = 0

                def poke(self):
                    self.seq += 1
                    self._wake.notify_all()
        """)
        fs = _run(tmp_path)
        assert _rules(fs) == ["condvar-misuse"]
        assert "notify" in fs[0].message

    def test_predicate_loop_and_held_notify_negative(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import threading

            class Sched:
                def __init__(self):
                    self._wake = threading.Condition()
                    self.seq = 0

                def idle(self, seen):
                    with self._wake:
                        while self.seq == seen:
                            self._wake.wait(0.05)

                def idle2(self, pred):
                    with self._wake:
                        self._wake.wait_for(pred, timeout=0.05)

                def poke(self):
                    with self._wake:
                        self.seq += 1
                        self._wake.notify_all()
        """)
        assert _run(tmp_path) == []

    def test_pragma_suppresses(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import threading

            class Sched:
                def __init__(self):
                    self._wake = threading.Condition()
                    self.seq = 0

                def idle(self, seen):
                    with self._wake:
                        if self.seq == seen:
                            # srtb-lint: disable=condvar-misuse
                            self._wake.wait(0.05)
        """)
        assert _run(tmp_path) == []


# ------------------------------------------------------ check-then-act


class TestCheckThenAct:
    SRC = """
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self.active = False
                t = threading.Thread(target=self._pump)
                t.start()

            def _pump(self):
                with self._lock:
                    self.active = True

            def stop(self):
                {body}
    """

    def test_test_outside_lock_positive(self, tmp_path):
        # every MUTATION is locked, so unguarded-shared-state stays
        # silent — but the test escaping the lock is the race this
        # rule exists for
        _write(tmp_path, "mod.py", self.SRC.format(body="""if self.active:
                    with self._lock:
                        self.active = False"""))
        fs = _run(tmp_path)
        assert _rules(fs) == ["check-then-act"]
        assert "active" in fs[0].message

    def test_whole_statement_locked_negative(self, tmp_path):
        _write(tmp_path, "mod.py", self.SRC.format(body="""with self._lock:
                    if self.active:
                        self.active = False"""))
        assert _run(tmp_path) == []

    def test_unshared_attr_negative(self, tmp_path):
        # no thread-entry ever touches it: plain single-threaded
        # check-then-set is fine
        _write(tmp_path, "mod.py", """
            class Cache:
                def __init__(self):
                    self.warm = False

                def ensure(self):
                    if not self.warm:
                        self.warm = True
        """)
        assert _run(tmp_path) == []

    def test_pragma_suppresses(self, tmp_path):
        _write(tmp_path, "mod.py", self.SRC.format(
            body="""# lifecycle-exclusive: stop() runs post-join
                # srtb-lint: disable=check-then-act
                if self.active:
                    with self._lock:
                        self.active = False"""))
        assert _run(tmp_path) == []


# ----------------------------------------- baseline workflow per rule


BASELINE_FIXTURES = {
    "lock-order-inversion": """
        import threading

        class E:
            def __init__(self):
                self.a_lock = threading.Lock()
                self.b_lock = threading.Lock()

            def f(self):
                with self.a_lock:
                    with self.b_lock:
                        pass

            def g(self):
                with self.b_lock:
                    with self.a_lock:
                        pass
    """,
    "blocking-under-lock": """
        import os
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def flush(self, fd):
                with self._lock:
                    os.fdatasync(fd)
    """,
    "condvar-misuse": """
        import threading

        class S:
            def __init__(self):
                self._cv = threading.Condition()
                self.seq = 0

            def idle(self, seen):
                with self._cv:
                    if self.seq == seen:
                        self._cv.wait(0.05)
    """,
    "check-then-act": """
        import threading

        class P:
            def __init__(self):
                self._lock = threading.Lock()
                self.active = False
                threading.Thread(target=self._pump).start()

            def _pump(self):
                with self._lock:
                    self.active = True

            def stop(self):
                if self.active:
                    with self._lock:
                        self.active = False
    """,
}


@pytest.mark.parametrize("rule", sorted(BASELINE_FIXTURES))
def test_baseline_accepts_rule(rule, tmp_path):
    _write(tmp_path, "src/mod.py", BASELINE_FIXTURES[rule])
    bl = str(tmp_path / "baseline.json")
    src = str(tmp_path / "src")
    assert lint.main([src, "--baseline", bl]) == 1  # new finding
    assert lint.main([src, "--baseline", bl, "--write-baseline"]) == 0
    assert lint.main([src, "--baseline", bl]) == 0  # accepted


# --------------------------------------------------- runtime: lockdep


class TestLockdepRuntime:
    def test_cycle_trap(self):
        ts = Tsan()
        a, b = ts.lock("A"), ts.lock("B")
        with a:
            with b:
                pass
        with pytest.raises(TsanError, match="inversion"):
            with b:
                with a:
                    pass

    def test_consistent_order_quiet(self):
        ts = Tsan()
        a, b, c = ts.lock("A"), ts.lock("B"), ts.lock("C")
        for _ in range(3):
            with a:
                with b:
                    with c:
                        pass
        assert ts.report()["order_edges"] >= 2

    def test_reacquire_trap(self):
        ts = Tsan()
        a = ts.lock("A")
        with pytest.raises(TsanError, match="re-acquire"):
            with a:
                with a:
                    pass

    def test_transitive_cycle_trap(self):
        # A->B and B->C on record; taking A under C closes the cycle
        # through the path, not a direct edge
        ts = Tsan()
        a, b, c = ts.lock("A"), ts.lock("B"), ts.lock("C")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with pytest.raises(TsanError, match="inversion"):
            with c:
                with a:
                    pass

    def test_stall_recorded_not_raised(self):
        ts = Tsan(stall_s=0.01)
        a = ts.lock("slow")
        with a:
            time.sleep(0.05)
        assert ts.stalls and ts.stalls[0][0] == "slow"
        assert ts.stalls[0][1] >= 0.01

    def test_condition_wait_notify_roundtrip(self):
        ts = Tsan()
        cv = ts.condition("cv")
        state = {"ready": False}

        def waker():
            time.sleep(0.02)
            with cv:
                state["ready"] = True
                cv.notify_all()

        t = threading.Thread(target=waker)
        t.start()
        with cv:
            while not state["ready"]:
                assert cv.wait(1.0)
        t.join()
        assert state["ready"]

    def test_condition_misuse_traps(self):
        ts = Tsan()
        cv = ts.condition("cv")
        with pytest.raises(TsanError, match="notify"):
            cv.notify_all()
        with pytest.raises(TsanError, match="wait"):
            cv.wait(0.01)


# ------------------------------------------------- runtime: ownership


class TestOwnership:
    def test_claim_on_first_use_trap(self):
        ts = Tsan()
        ts.assert_owner("lane.s0.step")  # main thread claims
        err = []

        def intruder():
            try:
                ts.assert_owner("lane.s0.step")
            except TsanError as e:
                err.append(e)

        t = threading.Thread(target=intruder, name="intruder")
        t.start()
        t.join()
        assert err and "ownership" in str(err[0])

    def test_release_prefix_allows_reclaim(self):
        ts = Tsan()
        ts.assert_owner("lane.s0.sink")
        ts.assert_owner("former.groups")
        ts.release_owners("lane.s0.sink")
        ok = []

        def successor():
            ts.assert_owner("lane.s0.sink")  # re-claim after restart
            try:
                ts.assert_owner("former.groups")
            except TsanError:
                ok.append(True)

        t = threading.Thread(target=successor)
        t.start()
        t.join()
        assert ok, "unreleased claim must still trap"


# ----------------------------------------- fleet integration + 0-cost


def _tiny_fleet(tmp_path, **cfg_kw):
    from srtb_tpu.config import Config
    from srtb_tpu.io.synth import make_dispersed_baseband
    from srtb_tpu.pipeline.fleet import StreamFleet, StreamSpec
    n = 1 << 12
    specs = []
    for i, name in enumerate(("s0", "s1")):
        bb = os.path.join(str(tmp_path), f"bb_{name}.bin")
        make_dispersed_baseband(
            n * 2, 1405.0, 64.0, 0.05, pulse_positions=[n // 2],
            pulse_amp=30.0, nbits=8, seed=i).tofile(bb)
        cfg = dict(
            baseband_input_count=n, baseband_input_bits=8,
            baseband_freq_low=1405.0, baseband_bandwidth=64.0,
            baseband_sample_rate=128e6, dm=0.05,
            input_file_path=bb,
            baseband_output_file_prefix=os.path.join(
                str(tmp_path), f"out_{name}_"),
            spectrum_channel_count=64,
            mitigate_rfi_average_method_threshold=100.0,
            mitigate_rfi_spectral_kurtosis_threshold=2.0,
            baseband_reserve_sample=True, writer_thread_count=0,
            fft_strategy="four_step", inflight_segments=2,
            retry_backoff_base_s=0.001)
        cfg.update(cfg_kw)
        specs.append(StreamSpec(name=name, cfg=Config(**cfg)))
    return StreamFleet(specs)


def test_fleet_tsan_on_runs_clean(tmp_path):
    fleet = _tiny_fleet(tmp_path, tsan=True)
    assert fleet._tsan is not None
    assert isinstance(fleet._wake, InstrumentedCondition)
    res = fleet.run()
    try:
        assert all(r.status == "done" for r in res.values())
        for lane in fleet.lanes.values():
            assert isinstance(lane._live_lock, InstrumentedLock)
        rep = fleet._tsan.report()
        # claims were released at run() exit (per-run ownership);
        # the order graph persists across the run
        assert rep["owners"] == {}
        assert "stalls" in rep and "order_edges" in rep
    finally:
        fleet.close()


def test_fleet_tsan_off_is_zero_cost(tmp_path):
    fleet = _tiny_fleet(tmp_path)  # tsan defaults off
    assert fleet._tsan is None
    assert isinstance(fleet._wake, threading.Condition)
    res = fleet.run()
    try:
        assert all(r.status == "done" for r in res.values())
        # lane locks are plain threading primitives — no wrapper
        # indirection anywhere on the hot path when the knob is off
        for lane in fleet.lanes.values():
            assert not isinstance(lane._live_lock, InstrumentedLock)
    finally:
        fleet.close()


# --------------------------------------- seeded schedule perturbation


class TestSchedulePerturber:
    def test_same_seed_same_schedule_same_journal(self):
        # driven with an identical (deterministic, single-threaded)
        # acquisition sequence, two perturbers with the same seed
        # perturb the same occurrences => identical journals
        seq = (["fleet._wake"] * 40 + ["lane.s0._live_lock"] * 40
               + ["fleet._wake", "lane.s1._live_lock"] * 20)
        p1 = SchedulePerturber(42, rate=0.3, sleep_s=0.0)
        p2 = SchedulePerturber(42, rate=0.3, sleep_s=0.0)
        for site in seq:
            p1.perturb(site)
        for site in seq:
            p2.perturb(site)
        assert p1.journal and p1.journal == p2.journal

    def test_different_seed_different_schedule(self):
        sites = [("s", k) for k in range(256)]
        p1 = SchedulePerturber(1, rate=0.3)
        p2 = SchedulePerturber(2, rate=0.3)
        assert [p1.decide(s, k) for s, k in sites] \
            != [p2.decide(s, k) for s, k in sites]

    def test_decide_is_pure(self):
        p = SchedulePerturber(9, rate=0.5)
        before = [p.decide("x", k) for k in range(64)]
        p.perturb("x")  # mutating the counter must not move decide()
        assert [p.decide("x", k) for k in range(64)] == before

    def test_install_uninstall(self):
        from srtb_tpu.analysis.tsan import current_perturber
        p = SchedulePerturber(0, rate=1.0, sleep_s=0.0)
        install_perturber(p)
        try:
            assert current_perturber() is p
            ts = Tsan()
            with ts.lock("L"):
                pass
            assert p.journal == [("L", 0)]
        finally:
            uninstall_perturber()
        assert current_perturber() is None


def test_race_soak_selftest_is_sharp():
    from srtb_tpu.tools.race_soak import selftest
    assert selftest() == []


@pytest.mark.slow
def test_race_soak_smoke(tmp_path):
    from srtb_tpu.tools.race_soak import run_race_soak
    report = run_race_soak(streams=2, segments=3, log2n=12, seed=1,
                           batch=2)
    assert report["ok"] and report["perturbs"] > 0


# ------------------------------------------ thread creation-site tags


def test_tag_thread_reports_creation_site():
    # tag_thread attributes to the first frame OUTSIDE the calling
    # module (the wrapper is not the interesting site), so a direct
    # call from here records OUR caller; the Pipe test below pins the
    # exact-attribution contract.  Here: a site exists and is file:line
    from srtb_tpu.utils import termination
    t = threading.Thread(target=lambda: None)
    termination.tag_thread(t)
    site = termination.created_at(t)
    assert site and re.match(r".+:\d+$", site)
    assert "created at" in termination.describe_threads([t])


def test_pipe_thread_carries_creation_site():
    from srtb_tpu.pipeline import framework as fw
    from srtb_tpu.utils import termination
    stop = fw.StopToken()
    pipe = fw.Pipe(lambda *_: None, None, None, stop)
    site = termination.created_at(pipe.thread)
    # the site is the CALLER of the framework, not framework.py itself
    assert site and "test_tsan.py" in site
    desc = termination.format_thread_stacks([pipe.thread])
    assert "created at" in desc
