"""Coherent-dedispersion chirp tests.

Oracle style follows test-df64.cpp: compare the two-float (df64) phase
factors against float64 computation (ref: tests/test-df64.cpp:28-60), plus
direct checks of the phase formula (Jiang 2022 / reference
coherent_dedispersion.hpp:133-150) and nsamps_reserved.
"""

import jax
import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.ops import dedisperse as dd


def _phase_oracle(n, f_min, df, f_c, dm):
    i = np.arange(n, dtype=np.float64)
    f = f_min + df * i
    k = dd.D * 1e6 * dm / f * ((f - f_c) / f_c) ** 2
    return np.modf(k)[0]


def test_chirp_host_matches_formula():
    n = 4096
    f_min, bw, dm = 1405.0 + 32.0, -64.0, -478.80  # J1644-4559 config values
    f_c = f_min + bw
    df = bw / n
    chirp = dd.chirp_factor_host(n, f_min, df, f_c, dm)
    k_frac = _phase_oracle(n, f_min, df, f_c, dm)
    expected = np.exp(-2j * np.pi * k_frac)
    np.testing.assert_allclose(chirp, expected.astype(np.complex64),
                               atol=1e-6)
    np.testing.assert_allclose(np.abs(chirp), 1.0, atol=1e-6)


def test_chirp_df64_matches_host():
    """df64 on-device chirp vs f64 host chirp: phase error must stay far
    below what f32 alone could achieve (delta-phi reaches ~1e7 turns at this
    DM; f32 would be pure noise)."""
    n = 8192
    f_min, bw, dm = 1000.0, 500.0, 100.0
    f_c = f_min + bw
    df = bw / n
    host = dd.chirp_factor_host(n, f_min, df, f_c, dm)
    dev = np.asarray(jax.jit(
        lambda: dd.chirp_factor_df64(n, f_min, df, f_c, dm))())
    # compare phase angles of unit phasors
    err = np.abs(np.angle(dev * np.conj(host)))
    assert np.max(err) < 5e-3, f"max phase error {np.max(err)}"
    assert np.mean(err) < 5e-4


def test_dispersion_delay_matches_reference_formula():
    # delay = -D*dm*(1/f^2 - 1/f_c^2) (ref: coherent_dedispersion.hpp:75-78)
    f, f_c, dm = 1469.0, 1405.0, 478.80
    delay = dd.dispersion_delay_time(f, f_c, dm)
    expected = -4.148808e3 * dm * (1.0 / f**2 - 1.0 / f_c**2)
    assert abs(delay - expected) < 1e-12


def test_nsamps_reserved():
    cfg = Config(baseband_input_count=1 << 23,
                 spectrum_channel_count=1 << 8,
                 baseband_freq_low=1405.0, baseband_bandwidth=64.0,
                 baseband_sample_rate=128e6, dm=75.0,
                 baseband_reserve_sample=True)
    reserved = dd.nsamps_reserved(cfg)
    minimal = 2 * round(dd.max_delay_time(1405.0, 64.0, 75.0) * 128e6)
    per_bin = 2 * cfg.spectrum_channel_count
    refft = (cfg.baseband_input_count - minimal) // per_bin * per_bin
    assert refft > 0
    assert reserved == cfg.baseband_input_count - refft
    assert reserved >= minimal
    # non-reserved part must tile into waterfall bins exactly
    assert (cfg.baseband_input_count - reserved) % per_bin == 0
    # disabled overlap
    assert dd.nsamps_reserved(cfg.replace(baseband_reserve_sample=False)) == 0
    # reserve larger than the segment: reference disables overlap (ref:
    # coherent_dedispersion.hpp:118-127)
    assert dd.nsamps_reserved(cfg.replace(baseband_input_count=1 << 20)) == 0


def test_dedisperse_removes_dispersion():
    """End-to-end physics check: dispersing then coherently dedispersing a
    band-limited impulse restores its peak."""
    n = 1 << 14
    sample_rate = 64e6  # 64 MHz band in complex sampling
    f_min, bw = 1200.0, 32.0
    dm = 30.0
    f_c = f_min + bw
    df = bw / n
    rng = np.random.default_rng(7)

    # impulse in time domain -> flat spectrum
    x = np.zeros(n, dtype=np.complex64)
    x[n // 2] = 1.0
    spec = np.fft.fft(x)
    # apply dispersion (conjugate chirp), then dedisperse with our op
    chirp = dd.chirp_factor_host(n, f_min, df, f_c, dm)
    dispersed_spec = spec * np.conj(chirp)
    dispersed = np.fft.ifft(dispersed_spec)
    # dispersed impulse is smeared: peak greatly reduced
    assert np.max(np.abs(dispersed)) < 0.5

    rededispersed = np.fft.ifft(
        np.asarray(dd.dedisperse(dispersed_spec.astype(np.complex64),
                                 chirp)))
    peak = np.max(np.abs(rededispersed))
    assert peak > 0.99, f"dedispersed peak {peak}"
    del sample_rate, rng


def test_anchored_fast_path_engages_and_matches_host():
    """The anchored-Taylor df64 chirp (concrete dm) must engage for the
    flagship J1644 parameters and match the f64 host chirp as tightly as
    the exact per-element path (~df64's inherent k*2^-48)."""
    import jax

    n = 1 << 20
    f_min, bw, dm = 1405.0 + 32.0, -64.0, -478.80
    df_ = bw / n
    f_c = f_min + bw
    assert dd.anchored_chirp_consts(n, f_min, df_, f_c, dm) is not None
    host = dd.chirp_factor_host(n, f_min, df_, f_c, dm)
    dev = np.asarray(jax.jit(
        lambda: dd.chirp_factor_df64(n, f_min, df_, f_c, dm))())
    assert np.abs(dev - host).max() < 2e-5


def test_anchored_matches_exact_traced_dm_path():
    """Anchored (concrete dm) and exact (traced hi/lo dm, the DM-search
    spelling) must agree in factor space — same function, two routes."""
    import jax
    import jax.numpy as jnp

    n = 1 << 16
    f_min, bw, dm = 1405.0, 64.0, 750.25
    df_ = bw / n
    f_c = f_min + bw
    anchored = np.asarray(jax.jit(
        lambda: dd.chirp_factor_df64(n, f_min, df_, f_c, dm))())
    dm_hi = jnp.float32(np.float32(dm))
    dm_lo = jnp.float32(np.float64(dm) - np.float32(dm))
    exact = np.asarray(jax.jit(
        lambda: dd.chirp_factor_df64(n, f_min, df_, f_c, dm_hi,
                                     dm_lo=dm_lo))())
    # both routes carry their own ~1e-5-class df64 error at this k;
    # absolute precision is pinned against the f64 host chirp above
    assert np.abs(anchored - exact).max() < 5e-5


def test_anchored_rejects_invalid_configs():
    """Traced dm, bands touching f = 0, and out-of-tolerance remainders
    must all fall back (None) rather than produce silent phase error."""
    import jax

    n = 1 << 14
    seen = []

    def probe(dm):
        seen.append(dd.anchored_chirp_consts(n, 1405.0, 64.0 / n,
                                             1469.0, dm))
        return dm

    jax.jit(probe)(10.0)  # dm traced inside jit
    assert seen[0] is None
    assert dd.anchored_chirp_consts(
        n, -32.0, 64.0 / n, 32.0, 10.0) is None  # band crosses zero
    # pathological: enormous DM over a band reaching ~0 -> remainder
    # blows past tolerance even at the minimum 32-channel block
    assert dd.anchored_chirp_consts(
        n, 1e-3, 1.0, 1e4, 1e9, allow_shrink=False) is None


def test_anchored_dm_linear_traced_path_matches_host():
    """The DM-search spelling: unit-dm anchor coefficients scaled by a
    *traced* per-trial dm (anchor_consts route) must match the f64 host
    chirp for every trial in the grid, including a traced i0 offset."""
    import jax
    import jax.numpy as jnp

    n = 1 << 16
    f_min, bw = 1405.0, 64.0
    df_ = bw / n
    f_c = f_min + bw
    dm_list = [12.5, 478.80, 993.12]
    consts = dd.anchored_chirp_consts(
        n, f_min, df_, f_c, max(dm_list), unit_dm=True)
    assert consts is not None

    @jax.jit
    def gen(dm_hi, dm_lo, i0):
        return dd.chirp_factor_df64_ri(n // 2, f_min, df_, f_c, dm_hi,
                                       i0=i0, dm_lo=dm_lo,
                                       anchor_consts=consts)

    for dm in dm_list:
        dm_hi = jnp.float32(np.float32(dm))
        dm_lo = jnp.float32(np.float64(dm) - np.float32(dm))
        for i0 in (0, n // 2):
            ri = np.asarray(gen(dm_hi, dm_lo, jnp.int32(i0)))
            got = ri[0] + 1j * ri[1]
            host = dd.chirp_factor_host(n, f_min, df_, f_c, dm)
            want = host[i0:i0 + n // 2]
            assert np.abs(got - want).max() < 5e-5, (dm, i0)
