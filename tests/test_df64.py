"""df64 two-float arithmetic tests vs float64 (oracle style follows the
reference's test-df64.cpp:28-60 + tests/test-df64.py numpy cross-check)."""

import jax
import numpy as np

from srtb_tpu.ops import df64 as ds


def _as_f64(pair):
    return ds.to_float64(tuple(np.asarray(p) for p in pair))


def test_roundtrip():
    rng = np.random.default_rng(0)
    x = rng.standard_normal(1000) * 1e8
    hi, lo = ds.from_float64(x)
    np.testing.assert_allclose(_as_f64((hi, lo)), x, rtol=1e-14)


def test_add_mul_div_precision():
    rng = np.random.default_rng(1)
    a = rng.standard_normal(1000) * 1e6
    b = rng.standard_normal(1000) * 1e3 + 2000.0
    a_d = tuple(map(np.asarray, ds.from_float64(a)))
    b_d = tuple(map(np.asarray, ds.from_float64(b)))

    def run(op):
        return _as_f64(jax.jit(lambda x, y: op(x, y))(a_d, b_d))

    # input representation error is ~|a| * 2^-50, which becomes the absolute
    # error floor under cancellation in add
    np.testing.assert_allclose(run(ds.add), a + b, rtol=1e-12, atol=1e-8)
    np.testing.assert_allclose(run(ds.mul), a * b, rtol=1e-12)
    np.testing.assert_allclose(run(ds.div), a / b, rtol=1e-12)


def test_frac_large_values():
    """Fraction extraction at k ~ 1e9, the dedispersion use case
    (ref: coherent_dedispersion.hpp:49)."""
    k = np.array([1.23456789e9 + 0.625, -9.876543e8 - 0.25, 3.0, -0.75])
    k_d = tuple(map(np.asarray, ds.from_float64(k)))
    frac = np.asarray(jax.jit(ds.frac)(k_d))
    expected = np.modf(k)[0]
    np.testing.assert_allclose(frac, expected, atol=2e-5)


def test_df64_chirp_high_channel_offset():
    """Channel indices beyond 2^24 are inexact in float32; the integer
    hi/lo split must keep the df64 phase accurate at e.g. i ~ 2^27
    (a 2^28-sample segment's upper channels)."""
    from srtb_tpu.ops import dedisperse as dd
    n_total = 1 << 28
    n_spec = n_total // 2
    f_min, bw, dm = 1405.0, -64.0, -478.80
    f_c = f_min + bw
    df = bw / n_spec
    i0 = 1 << 26                   # mid-band: worst f32-index phase error
    block = 1024
    got = np.asarray(dd.chirp_factor_df64(block, f_min, df, f_c, dm,
                                          i0=i0))
    i = np.arange(i0, i0 + block, dtype=np.float64)
    f = f_min + df * i
    delta_f = f - f_c
    k = (dd.D * 1e6) * dm / f * (delta_f / f_c) ** 2
    expected = np.exp(-2j * np.pi * np.modf(k)[0]).astype(np.complex64)
    err = np.abs(got - expected)
    assert err.max() < 5e-3, err.max()


def test_df64_survives_jit_compilation():
    """XLA's simplifier must not strip the error-free transforms: jitted
    and eager df64 chirp phases have to agree (they diverged by ~1 rad
    before optimization_barrier was added)."""
    import jax
    from srtb_tpu.ops import dedisperse as dd
    n = 512
    i0 = (1 << 26) + 1024
    f_min, bw, dm = 1437.0, -64.0, -478.80
    f_c = f_min + bw
    df = bw / (1 << 27)
    eager = np.asarray(dd._chirp_phase_df64(n, f_min, df, f_c, dm, i0=i0))
    jitted = np.asarray(jax.jit(
        lambda: dd._chirp_phase_df64(n, f_min, df, f_c, dm, i0=i0))())
    np.testing.assert_allclose(jitted, eager, rtol=0, atol=1e-4)
    # and the jitted phase matches float64 truth
    i = np.arange(i0, i0 + n, dtype=np.float64)
    f = f_min + df * i
    k = (dd.D * 1e6) * dm / f * ((f - f_c) / f_c) ** 2
    expected = -2 * np.pi * np.modf(k)[0]
    err = np.abs(jitted - expected)
    err = np.minimum(err, 2 * np.pi - err)
    assert err.max() < 2e-3, err.max()
