"""df64 two-float arithmetic tests vs float64 (oracle style follows the
reference's test-df64.cpp:28-60 + tests/test-df64.py numpy cross-check)."""

import jax
import numpy as np

from srtb_tpu.ops import df64 as ds


def _as_f64(pair):
    return ds.to_float64(tuple(np.asarray(p) for p in pair))


def test_roundtrip():
    rng = np.random.default_rng(0)
    x = rng.standard_normal(1000) * 1e8
    hi, lo = ds.from_float64(x)
    np.testing.assert_allclose(_as_f64((hi, lo)), x, rtol=1e-14)


def test_add_mul_div_precision():
    rng = np.random.default_rng(1)
    a = rng.standard_normal(1000) * 1e6
    b = rng.standard_normal(1000) * 1e3 + 2000.0
    a_d = tuple(map(np.asarray, ds.from_float64(a)))
    b_d = tuple(map(np.asarray, ds.from_float64(b)))

    def run(op):
        return _as_f64(jax.jit(lambda x, y: op(x, y))(a_d, b_d))

    # input representation error is ~|a| * 2^-50, which becomes the absolute
    # error floor under cancellation in add
    np.testing.assert_allclose(run(ds.add), a + b, rtol=1e-12, atol=1e-8)
    np.testing.assert_allclose(run(ds.mul), a * b, rtol=1e-12)
    np.testing.assert_allclose(run(ds.div), a / b, rtol=1e-12)


def test_frac_large_values():
    """Fraction extraction at k ~ 1e9, the dedispersion use case
    (ref: coherent_dedispersion.hpp:49)."""
    k = np.array([1.23456789e9 + 0.625, -9.876543e8 - 0.25, 3.0, -0.75])
    k_d = tuple(map(np.asarray, ds.from_float64(k)))
    frac = np.asarray(jax.jit(ds.frac)(k_d))
    expected = np.modf(k)[0]
    np.testing.assert_allclose(frac, expected, atol=2e-5)
