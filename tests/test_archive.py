"""Archive replay engine (pipeline/archive.py + tools/archive_replay):
fleet-fanned, micro-batched, exactly-once replay of recorded baseband
with deterministic resume."""

import json
import os

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.io.file_input import (DETERMINISTIC_EPOCH_NS,
                                    DeterministicTimestampReader,
                                    make_file_source)
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.pipeline.archive import ArchiveReplay, stream_name_for
from srtb_tpu.pipeline.runtime import Pipeline
from srtb_tpu.tools.archive_replay import (_make_archive_file,
                                           _science_cfg, _sha_map)
from srtb_tpu.utils.metrics import metrics

N = 1 << 12


@pytest.fixture(autouse=True)
def _fresh_metrics():
    metrics.reset()
    yield
    metrics.reset()


def _files(tmp_path, count=2, segments=3):
    return [_make_archive_file(str(tmp_path), f"bb{i}", N, segments,
                               seed=i) for i in range(count)]


def _golden(tmp_path, files):
    gdir = os.path.join(str(tmp_path), "golden")
    os.makedirs(gdir, exist_ok=True)
    for i, f in enumerate(files):
        cfg = Config(**_science_cfg(N)).replace(
            input_file_path=f,
            baseband_output_file_prefix=os.path.join(
                gdir, f"bb{i}_"),
            deterministic_timestamps=True, inflight_segments=2)
        with Pipeline(cfg) as pipe:
            pipe.run()
    return _sha_map(gdir)


# ------------------------------------------------------------------
# deterministic reader promotion (the crash-soak class, first-class)


def test_deterministic_reader_stamps_from_offset(tmp_path):
    path = os.path.join(str(tmp_path), "bb.bin")
    make_dispersed_baseband(N * 2, 1405.0, 64.0, 0.0,
                            pulse_positions=[], nbits=8).tofile(path)
    cfg = Config(**_science_cfg(N)).replace(
        input_file_path=path, deterministic_timestamps=True)
    r1 = make_file_source(cfg)
    assert isinstance(r1, DeterministicTimestampReader)
    stamps1 = [w.timestamp for w in r1]
    r1.close()
    r2 = make_file_source(cfg)
    stamps2 = [w.timestamp for w in r2]
    r2.close()
    assert stamps1 == stamps2
    assert stamps1[0] == DETERMINISTIC_EPOCH_NS
    # overlap-save: stamps advance by the stride, not the segment
    assert all(b > a for a, b in zip(stamps1, stamps1[1:]))
    # the wall-clock reader stays the default
    off = make_file_source(cfg.replace(deterministic_timestamps=False))
    assert not isinstance(off, DeterministicTimestampReader)
    off.close()


def test_pipeline_honors_deterministic_timestamps(tmp_path):
    """Two full pipeline runs of the same file produce the SAME
    artifact names and bytes (the property every replay gate rides)."""
    path = os.path.join(str(tmp_path), "bb.bin")
    make_dispersed_baseband(N * 2, 1405.0, 64.0, 0.05,
                            pulse_positions=[N // 2, N + N // 2],
                            pulse_amp=40.0, nbits=8).tofile(path)
    maps = []
    for tag in ("a", "b"):
        d = os.path.join(str(tmp_path), tag)
        os.makedirs(d)
        cfg = Config(**_science_cfg(N)).replace(
            input_file_path=path,
            baseband_output_file_prefix=os.path.join(d, "out_"),
            deterministic_timestamps=True)
        with Pipeline(cfg) as pipe:
            pipe.run()
        maps.append(_sha_map(d))
    assert maps[0] == maps[1] and maps[0]


# ------------------------------------------------------------------
# the engine


def test_replay_bit_identical_to_streamed_goldens(tmp_path):
    files = _files(tmp_path)
    golden = _golden(tmp_path, files)
    out = os.path.join(str(tmp_path), "replay")
    rep = ArchiveReplay(Config(**_science_cfg(N)), files, out,
                        lanes=2, micro_batch=1, inflight=4).run()
    assert rep.failed == 0 and rep.drained == rep.segments > 0
    # one config projection -> ONE shared plan compile for both lanes
    assert rep.plan_compiles == 1
    assert _sha_map(out) == golden


def test_replay_micro_batch_decisions_identical(tmp_path):
    files = _files(tmp_path)
    golden = _golden(tmp_path, files)
    out = os.path.join(str(tmp_path), "replay_mb")
    rep = ArchiveReplay(Config(**_science_cfg(N)), files, out,
                        lanes=2, micro_batch=2, inflight=4).run()
    assert rep.failed == 0
    batch = _sha_map(out)
    # identical artifact SET = identical decisions; raw dumps bitwise
    assert set(batch) == set(golden)
    for name in golden:
        if name.endswith(".bin"):
            assert batch[name] == golden[name], name


def test_replay_resumes_deterministically(tmp_path):
    """A capped first pass (the crash stand-in) + an uncapped second
    pass produce EXACTLY the golden output set: checkpoints resume,
    the manifests keep artifacts exactly-once."""
    files = _files(tmp_path)
    golden = _golden(tmp_path, files)
    out = os.path.join(str(tmp_path), "resume")
    base = Config(**_science_cfg(N))
    rep1 = ArchiveReplay(base, files, out, lanes=2, micro_batch=1,
                         inflight=4, max_segments_per_file=2).run()
    assert rep1.drained > 0
    partial = _sha_map(out)
    assert set(partial) < set(golden)
    rep2 = ArchiveReplay(base, files, out, lanes=2, micro_batch=1,
                         inflight=4).run()
    assert rep2.failed == 0 and rep2.drained > 0
    assert _sha_map(out) == golden
    # third pass: nothing left to do, nothing changes
    rep3 = ArchiveReplay(base, files, out, lanes=2, micro_batch=1,
                         inflight=4).run()
    assert rep3.drained == 0 and _sha_map(out) == golden


def test_more_files_than_lanes_queue_behind_admission(tmp_path):
    files = _files(tmp_path, count=3, segments=2)
    out = os.path.join(str(tmp_path), "fan")
    rep = ArchiveReplay(Config(**_science_cfg(N)), files, out,
                        lanes=1, micro_batch=2, inflight=4).run()
    assert rep.failed == 0
    assert all(f["status"] == "done" for f in rep.files.values())
    assert rep.plan_compiles == 1  # still one shared plan


def test_corrupt_file_contained_to_its_lane(tmp_path):
    files = _files(tmp_path)
    bad = os.path.join(str(tmp_path), "bad.bin")
    with open(bad, "wb") as f:
        f.write(b"\x00" * 100)  # not even one segment
    out = os.path.join(str(tmp_path), "contained")
    # a truncated file still replays (zero-padded final segment) —
    # use a missing-at-open failure instead: delete after validation
    rep = ArchiveReplay(Config(**_science_cfg(N)), files + [bad], out,
                        lanes=2, micro_batch=1, inflight=4).run()
    # the short file yields its single zero-padded segment; the two
    # real files are untouched either way
    assert rep.files["bb0"]["status"] == "done"
    assert rep.files["bb1"]["status"] == "done"


def test_engine_validates_inputs(tmp_path):
    base = Config(**_science_cfg(N))
    with pytest.raises(ValueError, match="at least one"):
        ArchiveReplay(base, [], str(tmp_path))
    with pytest.raises(FileNotFoundError):
        ArchiveReplay(base, [os.path.join(str(tmp_path), "nope.bin")],
                      str(tmp_path))


def test_stream_name_dedup():
    taken = set()
    assert stream_name_for("/a/obs.bin", taken) == "obs"
    assert stream_name_for("/b/obs.bin", taken) == "obs.1"
    assert stream_name_for("/c/weird name!.raw", taken) == \
        "weird_name_"


def test_periodicity_replay_mode(tmp_path):
    """search_mode rides the base config into every lane: an archive
    replay in periodicity mode drains with the periodicity plan."""
    files = _files(tmp_path, count=1, segments=2)
    out = os.path.join(str(tmp_path), "period")
    base = Config(**_science_cfg(N)).replace(
        search_mode="periodicity")
    rep = ArchiveReplay(base, files, out, lanes=1, micro_batch=2,
                        inflight=4).run()
    assert rep.failed == 0 and rep.drained > 0


@pytest.mark.slow
def test_archive_selftest_gate():
    """The full CI gate: SIGTERM mid-replay + resume, bit-identical
    union, micro-batch tolerance leg (subprocess-heavy: slow)."""
    from srtb_tpu.tools.archive_replay import run_selftest
    report = run_selftest(segments=4, log2n=13)
    assert report["ok"] and report["killed_mid_run"]


def test_cli_report_shape(tmp_path, capsys):
    from srtb_tpu.tools import archive_replay as AR
    files = _files(tmp_path, count=1, segments=2)
    out = os.path.join(str(tmp_path), "cli")
    argv = ["--files", files[0], "--out-dir", out,
            "--micro-batch", "1", "--inflight", "2", "--lanes", "1"]
    for k, v in sorted(_science_cfg(N).items()):
        argv += ["--set",
                 f"{k}={int(v) if isinstance(v, bool) else v}"]
    assert AR.main(argv) == 0
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rep["ok"] and rep["drained"] > 0
    assert "segments_per_sec" in rep
