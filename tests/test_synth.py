"""Synthetic baseband generator tests: pack/unpack round trip per bit
width, and pulse recoverability through the dedispersion pipeline is
covered by test_pipeline (which builds on the same generator)."""

import jax.numpy as jnp
import numpy as np
import pytest

from srtb_tpu.io.synth import make_dispersed_baseband, pack_subbyte, quantize
from srtb_tpu.ops import unpack as U


@pytest.mark.parametrize("nbits", [1, 2, 4])
def test_pack_subbyte_roundtrip(nbits):
    rng = np.random.default_rng(nbits)
    vals = rng.integers(0, 1 << nbits, size=1024, dtype=np.uint8)
    packed = pack_subbyte(vals, nbits)
    unpacked = np.asarray(U.unpack(jnp.asarray(packed), nbits, None))
    np.testing.assert_array_equal(unpacked, vals.astype(np.float32))


@pytest.mark.parametrize("nbits", [1, 2, 4, 8, 16])
def test_quantize_width_and_range(nbits):
    rng = np.random.default_rng(0)
    sig = rng.standard_normal(4096)
    q = quantize(sig, nbits)
    assert q.dtype == np.uint8
    assert q.nbytes == 4096 * nbits // 8
    unpacked = np.asarray(U.unpack(jnp.asarray(q), nbits, None))
    assert unpacked.min() >= 0 and unpacked.max() <= (1 << min(nbits, 16)) - 1
    # quantization preserves the signal: correlation with the original
    # (1-bit caps at 2/pi ~ 0.8, coarse widths below fine ones)
    levels_mid = (1 << nbits) / 2
    c = np.corrcoef(sig, unpacked[:4096] - levels_mid)[0, 1]
    assert c > {1: 0.75, 2: 0.85}.get(nbits, 0.9), c


def test_dispersed_pulse_present_at_expected_delay():
    # the dispersed pulse must NOT be at its injection point in the raw
    # time series (it is smeared by the medium), total energy conserved
    n = 1 << 16
    quiet = make_dispersed_baseband(n, 1405.0, 64.0, 0.0, n // 2,
                                    nbits=8, pulse_amp=0.0)
    with_pulse = make_dispersed_baseband(n, 1405.0, 64.0, 30.0, n // 2,
                                         nbits=8, pulse_amp=40.0)
    assert with_pulse.shape == quiet.shape
    assert not np.array_equal(with_pulse, quiet)
