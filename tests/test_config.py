"""Config / expression-parsing tests (ref oracle: program_options.hpp
expression handling; example config userspace/srtb_config_1644-4559.cfg)."""

import os
import tempfile

from srtb_tpu.config import Config
from srtb_tpu.utils.expression import parse_expression, parse_number


def test_expressions():
    assert parse_expression("2 ** 30") == 2 ** 30
    assert parse_expression("1405 + (64 / 2)") == 1437.0
    assert parse_expression("128 * 1e6") == 128e6
    assert parse_number("-478.80") == -478.80
    assert parse_number("2 ** 11") == 2048


def test_config_file_roundtrip():
    text = """
# example config file (mirrors srtb_config_1644-4559.cfg)
baseband_input_count = 2 ** 20
spectrum_channel_count = 2 ** 11
log_level = 4
mitigate_rfi_average_method_threshold = 1.5
signal_detect_max_boxcar_length = 256
baseband_input_bits = 2
dm = -478.80
baseband_reserve_sample = 0
baseband_freq_low = 1405 + (64 / 2)
baseband_bandwidth = -64
baseband_sample_rate = 128 * 1e6
mitigate_rfi_freq_list = 1418-1422
"""
    with tempfile.NamedTemporaryFile("w", suffix=".cfg", delete=False) as f:
        f.write(text)
        path = f.name
    try:
        cfg = Config()
        cfg.load_file(path)
    finally:
        os.unlink(path)
    assert cfg.baseband_input_count == 2 ** 20
    assert cfg.spectrum_channel_count == 2048
    assert cfg.baseband_input_bits == 2
    assert cfg.dm == -478.80
    assert cfg.baseband_reserve_sample is False
    assert cfg.baseband_freq_low == 1437.0
    assert cfg.baseband_bandwidth == -64
    assert cfg.baseband_sample_rate == 128e6
    assert cfg.mitigate_rfi_freq_list == "1418-1422"


def test_cli_precedence():
    cfg = Config.from_args(["--dm=10.5", "--baseband-input-count", "2**16"])
    assert cfg.dm == 10.5
    assert cfg.baseband_input_count == 65536


def test_reference_config_key_parity():
    """Every runtime option of the reference (config.hpp srtb::configs +
    program_options.hpp extras) exists under the same name, so reference
    users can bring their .cfg files across unchanged."""
    from dataclasses import fields
    reference_keys = {
        # ref: userspace/include/srtb/config.hpp:80-249
        "baseband_bandwidth", "baseband_format_type", "baseband_freq_low",
        "baseband_input_bits", "baseband_input_count",
        "baseband_output_file_prefix", "baseband_reserve_sample",
        "baseband_sample_rate", "baseband_write_all", "config_file_name",
        "dm", "fft_fftw_wisdom_path", "gui_enable", "gui_pixmap_height",
        "gui_pixmap_width", "input_file_offset_bytes", "input_file_path",
        "mitigate_rfi_average_method_threshold", "mitigate_rfi_freq_list",
        "mitigate_rfi_spectral_kurtosis_threshold",
        "signal_detect_channel_threshold", "signal_detect_max_boxcar_length",
        "signal_detect_signal_noise_threshold", "spectrum_channel_count",
        "spectrum_sum_count", "thread_query_work_wait_time",
        # ref: program_options.hpp (CLI-only options)
        "udp_receiver_address", "udp_receiver_port",
        "udp_receiver_cpu_preferred", "log_level",
    }
    ours = {f.name for f in fields(Config)}
    missing = reference_keys - ours
    assert not missing, f"reference options without parity: {missing}"
