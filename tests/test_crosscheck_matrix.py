"""Crosscheck MATRIX: every packet format x every execution plan against
the independent float64 oracle (oracle_utils).

The reference validates its chain on real recordings in each ingest
format (ref: README.md:9-19, backend_registry.hpp:36-181); the closest
reproducible substitute is identical-bytes numeric parity per format and
per plan.  The single-format crosscheck (test_reference_crosscheck)
pins the default path deeply; this matrix widens it:

- axis 1, formats: simple 2/4-bit sub-byte, simple signed int8, the
  "1212" byte-interleave, the "1122" pair-interleave, and both gznupsr
  word-interleaves (incl. the XOR-0x80 unsigned->signed trick) — all
  multi-stream formats checked per stream against an *independent*
  de-interleave transliteration (oracle_utils.oracle_deinterleave).
- axis 2, plans: the fused single-program plan, the three-program
  staged plan (the 2^30 production form, forced small here), the
  Pallas in-step-chirp plan, and the MXU DFT-matmul FFT strategy.

Thresholds sit in the strict-parity tier (no RFI decision flips), so
any mismatch is a numeric/convention error, not a threshold race.
"""

import numpy as np
import pytest
from oracle_utils import oracle_deinterleave, oracle_stream_chain

from srtb_tpu.config import Config
from srtb_tpu.pipeline.segment import SegmentProcessor, waterfall_to_numpy

# (format, baseband_input_bits, data_stream_count)
FORMATS = [
    ("simple", 2, 1),
    ("simple", 4, 1),
    ("simple", -8, 1),
    ("interleaved_samples_2", -8, 2),
    ("naocpsr_snap1", -8, 2),
    ("gznupsr_a1", -8, 2),
    ("gznupsr_a1_v1", -8, 4),
]

PLANS = ["fused", "staged", "pallas", "pallas_sk", "mxu", "pallas2"]

N = 1 << 14


def _cfg(fmt: str, nbits: int) -> Config:
    return Config(
        baseband_input_count=N,
        baseband_input_bits=nbits,
        baseband_format_type=fmt,
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        dm=30.0,
        spectrum_channel_count=1 << 5,
        signal_detect_signal_noise_threshold=5.0,
        signal_detect_max_boxcar_length=8,
        mitigate_rfi_average_method_threshold=1e9,    # strict parity:
        mitigate_rfi_spectral_kurtosis_threshold=1e9,  # no decision flips
        baseband_reserve_sample=False,
    )


def _processor(cfg: Config, plan: str) -> SegmentProcessor:
    if plan == "fused":
        return SegmentProcessor(cfg)
    if plan == "staged":
        return SegmentProcessor(cfg, staged=True)
    if plan == "pallas":
        return SegmentProcessor(cfg.replace(use_pallas=True))
    if plan == "pallas_sk":
        # fused RFI+chirp front half AND the fused waterfall+SK-stats
        # epilogue (fft_rows_stats_ri + sk_apply_timeseries)
        return SegmentProcessor(cfg.replace(use_pallas=True,
                                            use_pallas_sk=True))
    if plan == "mxu":
        return SegmentProcessor(cfg.replace(fft_strategy="mxu"))
    if plan == "pallas2":
        # at this N the strategy takes its documented fallback (pallas
        # legs); the [2^24, 2^29] window itself is oracle-checked in
        # test_pallas_fft2 — this row pins the in-pipeline plumbing
        return SegmentProcessor(cfg.replace(fft_strategy="pallas2"))
    raise ValueError(plan)


def _raw_segment(cfg: Config, streams: int, seed: int = 7) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=cfg.segment_bytes(streams),
                        dtype=np.uint8)


def _check(fmt, nbits, streams, plan):
    cfg = _cfg(fmt, nbits)
    raw = _raw_segment(cfg, streams)
    proc = _processor(cfg, plan)
    wf = waterfall_to_numpy(proc.process(raw)[0])
    if wf.ndim == 2:
        wf = wf[None]
    assert wf.shape[0] == streams

    per_stream = oracle_deinterleave(raw, fmt, nbits)
    assert len(per_stream) == streams
    for s, x in enumerate(per_stream):
        wf_o, _, _ = oracle_stream_chain(x, cfg)
        scale = max(np.abs(wf_o).max(), 1e-30)
        np.testing.assert_allclose(
            wf[s], wf_o.astype(np.complex64),
            atol=3e-4 * scale, rtol=3e-3,
            err_msg=f"{fmt}/{nbits} stream {s} plan {plan}")


@pytest.mark.parametrize("fmt,nbits,streams", FORMATS,
                         ids=[f"{f}_{b}" for f, b, _ in FORMATS])
@pytest.mark.parametrize("plan", ["fused", "staged"])
def test_format_matrix(fmt, nbits, streams, plan):
    """Every ingest format, fused and staged plans, per-stream parity."""
    _check(fmt, nbits, streams, plan)


@pytest.mark.parametrize("fmt,nbits,streams",
                         [("simple", 2, 1), ("gznupsr_a1", -8, 2)],
                         ids=["simple_2", "gznupsr_a1"])
@pytest.mark.parametrize("plan", ["pallas", "pallas_sk", "mxu",
                                  "pallas2"])
def test_plan_matrix(fmt, nbits, streams, plan):
    """The alternate compute plans on the flagship sub-byte format and a
    word-interleaved multi-stream format."""
    _check(fmt, nbits, streams, plan)
