"""Config-space robustness sweep: a seeded sample of the full
(bit-width x window x reserve x channels x strategy) product through the
segment processor, plus the named edge corners that broke (or nearly
broke) during the round-3 fuzz campaign.

The full 270-combo sweep runs ~8 min; this keeps a representative
seeded slice in CI.  The campaign's catches are pinned individually:
the 64-bit-float view truncation (test_unpack), the distributed
non-dividing channel guard (test_parallel), and the duplicate-counter
block assembly (test_udp)."""

import itertools

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.pipeline.segment import SegmentProcessor, waterfall_to_numpy

FULL_GRID = list(itertools.product(
    [1, 2, 4, -8, 8],                       # bit widths
    ["rectangle", "hamming", "hann"],       # windows
    [False, True],                          # reserve overlap
    [1 << 5, 48, 1 << 7],                   # channel counts (incl. odd)
    ["auto", "four_step", "mxu"],           # fft strategies
))
rng = np.random.default_rng(20260730)
SAMPLE = [FULL_GRID[i] for i in
          rng.choice(len(FULL_GRID), size=24, replace=False)]


@pytest.mark.parametrize("nbits,win,reserve,chan,strat", SAMPLE)
def test_segment_processor_config_sample(nbits, win, reserve, chan, strat):
    n = 1 << 13
    cfg = Config(
        baseband_input_count=n, baseband_input_bits=nbits,
        baseband_format_type="simple", baseband_freq_low=1405.0,
        baseband_bandwidth=64.0, baseband_sample_rate=128e6, dm=5.0,
        spectrum_channel_count=chan, signal_detect_max_boxcar_length=8,
        baseband_reserve_sample=reserve, fft_strategy=strat)
    proc = SegmentProcessor(cfg, window_name=win)
    raw = np.random.default_rng(1).integers(
        0, 256, cfg.segment_bytes(1), dtype=np.uint8)
    wf = waterfall_to_numpy(proc.process(raw)[0])
    assert np.isfinite(wf).all()


EDGES = [
    ("chan-gt-nspec", dict(spectrum_channel_count=1 << 13)),
    ("chan-eq-nspec", dict(spectrum_channel_count=1 << 11)),
    ("boxcar-gt-wlen", dict(signal_detect_max_boxcar_length=4096)),
    ("boxcar-1", dict(signal_detect_max_boxcar_length=1)),
    ("tiny-n", dict(baseband_input_count=256, spectrum_channel_count=8)),
    ("bits16", dict(baseband_input_bits=16)),
    ("bits-16", dict(baseband_input_bits=-16)),
    ("bits64", dict(baseband_input_bits=64)),
    ("inverted-band", dict(baseband_freq_low=1437.0,
                           baseband_bandwidth=-64.0, dm=-478.80)),
    ("dm-zero", dict(dm=0.0)),
]


@pytest.mark.parametrize("tag,overrides", EDGES,
                         ids=[t for t, _ in EDGES])
def test_segment_processor_edge_corners(tag, overrides):
    base = dict(
        baseband_input_count=1 << 12, baseband_input_bits=2,
        baseband_format_type="simple", baseband_freq_low=1405.0,
        baseband_bandwidth=64.0, baseband_sample_rate=128e6, dm=5.0,
        spectrum_channel_count=1 << 5, signal_detect_max_boxcar_length=8,
        baseband_reserve_sample=False)
    base.update(overrides)
    cfg = Config(**base)
    proc = SegmentProcessor(cfg)
    r = np.random.default_rng(2)
    if cfg.baseband_input_bits in (32, 64):
        # float ingest: random BYTES would contain NaN/Inf bit patterns
        # (garbage in, NaN out — correctly); feed real sample values
        dt = np.float32 if cfg.baseband_input_bits == 32 else np.float64
        raw = np.frombuffer(
            r.standard_normal(cfg.baseband_input_count).astype(dt)
            .tobytes(), dtype=np.uint8)
    else:
        raw = r.integers(0, 256, cfg.segment_bytes(1), dtype=np.uint8)
    wf = waterfall_to_numpy(proc.process(raw)[0])
    assert np.isfinite(wf).all()
