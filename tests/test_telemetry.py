"""Telemetry-layer tests: histogram percentile math, sliding-window
rates, the JSONL segment-span journal (schema round-trip + rotation),
Prometheus text exposition, /healthz staleness, and the end-to-end
pipeline -> journal -> telemetry_report path on the CPU backend."""

import json
import math
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from srtb_tpu.utils import telemetry
from srtb_tpu.utils.metrics import (Histogram, Metrics, SlidingWindow,
                                    metrics)


# ---------------------------------------------------------------- units


def test_histogram_percentiles_interpolated():
    """Known uniform data over fine buckets: interpolated p50/p95/p99
    land within one bucket width of the exact percentile."""
    h = Histogram("t", buckets=[i / 100 for i in range(1, 101)])
    for i in range(1000):
        h.observe((i + 0.5) / 1000.0)  # uniform on (0, 1)
    assert abs(h.quantile(0.50) - 0.50) < 0.02
    assert abs(h.quantile(0.95) - 0.95) < 0.02
    assert abs(h.quantile(0.99) - 0.99) < 0.02
    p = h.percentiles()
    assert p["p50"] < p["p95"] < p["p99"]
    assert h.count == 1000
    assert abs(h.sum - 500.0) < 1.0


def test_histogram_edge_cases():
    h = Histogram("t", buckets=[1.0, 10.0])
    assert math.isnan(h.quantile(0.5))  # empty
    # everything in the overflow bucket clamps to the top finite edge
    for _ in range(5):
        h.observe(100.0)
    assert h.quantile(0.5) == 10.0
    # cumulative exposition: +Inf bucket equals the total count
    cum = h.cumulative_buckets()
    assert cum[-1] == (math.inf, 5)
    assert cum[0] == (1.0, 0)


def test_histogram_first_bucket_interpolates_from_zero():
    h = Histogram("t", buckets=[10.0, 20.0])
    for _ in range(10):
        h.observe(5.0)
    # rank q*10 inside the first bucket -> linear from 0 to 10
    assert abs(h.quantile(0.5) - 5.0) < 1e-9


def test_sliding_window_rate_and_pruning():
    t = [0.0]
    w = SlidingWindow("x", window_s=10.0, clock=lambda: t[0])
    for _ in range(5):
        w.add(2.0)
    t[0] = 5.0
    assert w.sum() == 10.0
    # young window: rate over elapsed time, not the full window
    assert abs(w.rate() - 10.0 / 5.0) < 1e-9
    # events age out
    t[0] = 10.5
    assert w.sum() == 0.0
    assert w.rate() == 0.0
    w.add(4.0)
    t[0] = 12.0
    assert w.sum() == 4.0
    assert abs(w.rate() - 4.0 / 10.0) < 1e-9  # mature: per window second


def test_metrics_registry_snapshot_and_reset():
    m = Metrics()
    m.add("segments", 3)
    m.histogram("stage_seconds", labels={"stage": "fetch"}).observe(0.02)
    m.window("segments", window_s=10.0).add(3)
    snap = m.snapshot()
    assert snap["segments"] == 3
    assert snap["stage_seconds_fetch_count"] == 1
    assert snap["stage_seconds_fetch_p50"] > 0
    assert snap["segments_per_sec_10s"] > 0
    # same (name, labels) -> same instrument; different labels -> new
    h1 = m.histogram("stage_seconds", labels={"stage": "fetch"})
    h2 = m.histogram("stage_seconds", labels={"stage": "sink"})
    assert h1.count == 1 and h2.count == 0
    m.reset()
    snap = m.snapshot()
    assert "segments" not in snap and "stage_seconds_fetch_count" \
        not in snap


def test_prometheus_exposition_format():
    m = Metrics()
    m.add("segments", 7)
    h = m.histogram("stage_seconds", buckets=[0.01, 0.1, 1.0],
                    labels={"stage": "dispatch"})
    h.observe(0.05)
    h.observe(0.05)
    h.observe(5.0)
    m.window("samples", window_s=10.0).add(100)
    text = m.prometheus()
    lines = text.strip().split("\n")
    assert text.endswith("\n")
    assert "# TYPE srtb_segments gauge" in lines
    assert "srtb_segments 7" in lines
    assert "# TYPE srtb_stage_seconds histogram" in lines
    # cumulative buckets with labels, +Inf bucket == count
    assert ('srtb_stage_seconds_bucket{le="0.01",stage="dispatch"} 0'
            in lines)
    assert ('srtb_stage_seconds_bucket{le="0.1",stage="dispatch"} 2'
            in lines)
    assert ('srtb_stage_seconds_bucket{le="+Inf",stage="dispatch"} 3'
            in lines)
    assert 'srtb_stage_seconds_count{stage="dispatch"} 3' in lines
    assert any(ln.startswith('srtb_stage_seconds_sum{stage="dispatch"}')
               for ln in lines)
    assert any(ln.startswith('srtb_samples_per_sec{window_s="10"}')
               for ln in lines)
    # every non-comment line is "name{labels} value" with a float value
    for ln in lines:
        if ln.startswith("#"):
            continue
        name_part, _, val = ln.rpartition(" ")
        assert name_part and float(val) == float(val)


def test_prometheus_help_type_conformance():
    """Exposition-format conformance: every family carries exactly one
    # HELP and one # TYPE line, HELP first, and all of a family's
    samples stay contiguous after its metadata (strict expfmt
    parsers reject re-opened families and samples before TYPE)."""
    m = Metrics()
    m.add("segments", 7)
    m.add("segments", 2, labels={"stream": "beam0"})
    m.add("custom_thing", 1)  # unknown family: generic HELP fallback
    m.add("only_labeled", 1, labels={"stream": "beam1"})
    m.histogram("stage_seconds", labels={"stage": "fetch"}).observe(0.1)
    m.window("samples", window_s=10.0).add(5)
    lines = m.prometheus().strip().split("\n")
    seen_help: dict[str, int] = {}
    seen_type: dict[str, int] = {}
    current = None
    families_order = []
    for ln in lines:
        if ln.startswith("# HELP "):
            name = ln.split()[2]
            seen_help[name] = seen_help.get(name, 0) + 1
            assert len(ln.split(" ", 3)) == 4 and ln.split(" ", 3)[3]
        elif ln.startswith("# TYPE "):
            name = ln.split()[2]
            seen_type[name] = seen_type.get(name, 0) + 1
            # HELP precedes TYPE for the same family
            assert seen_help.get(name) == seen_type[name]
            current = name
            families_order.append(name)
        else:
            sample = ln.split("{")[0].split(" ")[0]
            # a sample belongs to the most recently opened family
            # (histograms append _bucket/_sum/_count)
            assert sample == current or sample.startswith(
                current + "_"), (sample, current)
    # one HELP + one TYPE per family, no family opened twice
    assert seen_help == seen_type
    assert all(v == 1 for v in seen_type.values())
    assert len(families_order) == len(set(families_order))
    # known families get real help text, unknown the generic fallback
    text = "\n".join(lines)
    assert ("# HELP srtb_segments Segments drained end-to-end "
            "(lifetime)") in text
    assert "# HELP srtb_custom_thing srtb_tpu runtime metric" in text
    assert "# HELP srtb_only_labeled" in text
    assert "# HELP srtb_samples_per_sec" in text
    assert "# HELP srtb_stage_seconds" in text


def test_labeled_series_concurrent_with_scraper():
    """Satellite: fleet lanes hammer add/set(labels=) on one registry
    while a scraper snapshots — no torn reads, no lost updates, and
    the final totals are exact."""
    m = Metrics()
    n_threads, n_iter = 8, 2000
    stop = threading.Event()
    scrape_errors = []

    def scraper():
        while not stop.is_set():
            try:
                snap = m.snapshot()
                text = m.prometheus()
                # every rendered sample parses back as a float; the
                # labeled samples stay contiguous with their family
                for ln in text.strip().split("\n"):
                    if not ln.startswith("#"):
                        float(ln.rpartition(" ")[2])
                assert isinstance(snap, dict)
            except Exception as e:  # noqa: BLE001 - recorded, asserted
                scrape_errors.append(e)
                return

    def lane(i):
        labels = {"stream": f"beam{i % 4}"}
        for k in range(n_iter):
            m.add("segments_dropped", 1, labels=labels)
            m.add("segments_dropped", 1)  # flat twin
            m.set("inflight_depth", k % 5, labels=labels)

    threads = [threading.Thread(target=lane, args=(i,))
               for i in range(n_threads)]
    scr = threading.Thread(target=scraper)
    scr.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    scr.join()
    assert not scrape_errors, scrape_errors
    assert m.get("segments_dropped") == n_threads * n_iter
    per = m.by_label("segments_dropped")
    assert sum(per.values()) == n_threads * n_iter
    # 8 lanes over 4 stream labels: each label saw exactly 2 lanes
    assert set(per) == {f"beam{i}" for i in range(4)}
    assert all(v == 2 * n_iter for v in per.values())


def test_prometheus_includes_derived_series():
    """The derived scalars the JSON snapshot computes (loss rates,
    lifetime Msamples/s, elapsed) are exposed to Prometheus too — an
    alert written against either endpoint sees the other's values."""
    m = Metrics()
    m.add("samples", 2e6)
    m.add("packets_total", 100)
    m.add("packets_lost", 3)
    m.window("packets_total", window_s=60.0).add(100)
    m.window("packets_lost", window_s=60.0).add(3)
    text = m.prometheus()
    vals = {ln.rpartition(" ")[0]: float(ln.rpartition(" ")[2])
            for ln in text.strip().split("\n")
            if not ln.startswith("#")}
    assert abs(vals["srtb_packet_loss_rate"] - 0.03) < 1e-12
    assert abs(vals["srtb_packet_loss_rate_window"] - 0.03) < 1e-12
    assert "srtb_msamples_per_sec" in vals
    assert "srtb_elapsed_s" in vals
    snap = m.snapshot()
    assert abs(snap["packet_loss_rate_window"] - 0.03) < 1e-12


# ------------------------------------------------------------- journal


def test_span_journal_roundtrip_and_rotation(tmp_path):
    from srtb_tpu.tools import telemetry_report as TR
    from srtb_tpu.utils.telemetry import SpanJournal, segment_span

    path = str(tmp_path / "tele" / "journal.jsonl")
    with SpanJournal(path, max_bytes=1 << 20) as j:
        for i in range(3):
            j.write(segment_span(
                segment=i, stages_s={"ingest": 0.001, "dispatch": 0.01,
                                     "fetch": 0.1, "sink": 0.002},
                queue_depth=1, detections=i, dump=bool(i),
                samples=1 << 16, timestamp_ns=123))
    recs = TR.load(path)
    assert len(recs) == 3
    r = recs[-1]
    assert r["type"] == "segment_span" and r["v"] == 11
    assert r["segment"] == 2 and r["detections"] == 2 and r["dump"]
    assert r["samples"] == 1 << 16 and r["timestamp_ns"] == 123
    assert r["queue_depth"] == 1
    assert set(r["stages_ms"]) == {"ingest", "dispatch", "fetch", "sink"}
    assert r["stages_ms"]["fetch"] == 100.0
    assert "ts" in r and "packets_lost" in r

    # rotation: a tiny cap forces the previous generation out — gzip'd
    # to <path>.1.gz by default; load() reads both transparently
    small = str(tmp_path / "rot.jsonl")
    with SpanJournal(small, max_bytes=1400) as j:
        for i in range(10):
            j.write(segment_span(i, {"sink": 0.001}, 0, 0, False, 1))
    rotated = TR.load(small)
    assert (tmp_path / "rot.jsonl.1.gz").exists()
    assert not (tmp_path / "rot.jsonl.1").exists()
    # the active file never exceeds the cap; the newest spans and the
    # previous generation both survive, oldest first
    assert (tmp_path / "rot.jsonl").stat().st_size <= 1400
    segs = [r["segment"] for r in rotated]
    assert segs and segs[-1] == 9 and segs == sorted(segs)

    # legacy plaintext rotation still available (compress=False), and
    # the reader handles it identically
    plain = str(tmp_path / "plain.jsonl")
    with SpanJournal(plain, max_bytes=1400, compress=False) as j:
        for i in range(10):
            j.write(segment_span(i, {"sink": 0.001}, 0, 0, False, 1))
    assert (tmp_path / "plain.jsonl.1").exists()
    segs = [r["segment"] for r in TR.load(plain)]
    assert segs and segs[-1] == 9 and segs == sorted(segs)


def test_span_journal_write_failure_disables_not_raises(tmp_path):
    """Telemetry must never abort the observation: an I/O failure on
    append disables the journal instead of propagating."""
    from srtb_tpu.utils.telemetry import SpanJournal, segment_span

    j = SpanJournal(str(tmp_path / "j.jsonl"), max_bytes=1 << 20)
    j.write(segment_span(0, {"sink": 0.001}, 0, 0, False, 1))

    class _Broken:
        def write(self, _):
            raise OSError(28, "No space left on device")

        def close(self):
            pass

    j._file = _Broken()
    j.write(segment_span(1, {"sink": 0.001}, 0, 0, False, 1))  # no raise
    assert j._file is None
    j.write(segment_span(2, {"sink": 0.001}, 0, 0, False, 1))  # no-op
    j.close()


def test_telemetry_report_stats_and_timeline(tmp_path):
    from srtb_tpu.tools import telemetry_report as TR

    path = tmp_path / "j.jsonl"
    t0 = 1000.0
    with open(path, "w") as f:
        for i in range(100):
            f.write(json.dumps({
                "type": "segment_span", "v": 1, "ts": t0 + i * 0.5,
                "segment": i,
                "stages_ms": {"dispatch": float(i + 1), "sink": 1.0},
                "queue_depth": 1, "detections": 1, "dump": i % 2 == 0,
                "samples": 1 << 20,
                "packets_total": 10.0 * (i + 1),
                "packets_lost": float(i // 50),
            }) + "\n")
    rep = TR.report(str(path), bin_s=10.0)
    assert rep["records"] == 100
    st = rep["stages"]["dispatch"]
    # exact percentiles of 1..100 ms
    assert st["count"] == 100
    assert abs(st["p50_ms"] - 50.5) < 1e-6
    assert abs(st["p99_ms"] - 99.01) < 0.02
    assert st["max_ms"] == 100.0
    assert rep["stages"]["sink"]["p50_ms"] == 1.0
    # synthetic whole-segment stage = sum of the record's stages
    assert rep["stages"]["segment"]["max_ms"] == 101.0
    tl = rep["timeline"]
    assert len(tl) == 5  # 100 records * 0.5 s over 10 s bins
    assert tl[0]["segments"] == 20
    assert abs(tl[0]["segments_per_sec"] - 2.0) < 1e-9
    assert abs(tl[0]["msamples_per_sec"]
               - 20 * (1 << 20) / 10.0 / 1e6) < 1e-3  # rounded to 3dp
    # cumulative counter 0 -> 1 at i=50: one unit of loss localized
    assert sum(b["packets_lost_delta"] for b in tl) == 1
    assert tl[2]["packets_lost_delta"] == 1  # the bin holding i=50
    # the final bin is partial (records end at 49.5 s): its rate uses
    # the covered 9.5 s, not the 10 s width — no phantom slowdown
    assert abs(tl[-1]["segments_per_sec"] - 20 / 9.5) < 1e-3
    # markdown rendering + main() exit codes
    md = TR._md(rep)
    assert "| dispatch |" in md and "Msamples/s" in md
    assert TR.main([str(path)]) == 0
    # an empty (freshly-rotated) journal is a NOTE, not an error: CI
    # artifact stages must not fail a healthy run that simply has not
    # drained a segment yet
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert TR.main([str(empty)]) == 0


def test_report_json_matches_md_sections(tmp_path, capsys):
    """Satellite: --format json is machine-readable with the SAME
    sections the text report renders — CI/dashboards must not scrape
    human tables."""
    from srtb_tpu.tools import telemetry_report as TR
    from srtb_tpu.utils.telemetry import SpanJournal, segment_span

    path = str(tmp_path / "j.jsonl")
    with SpanJournal(path) as j:
        for i in range(4):
            j.write(segment_span(
                i, {"ingest": 0.001, "dispatch": 0.01, "fetch": 0.02,
                    "sink": 0.002}, 1, i % 2, bool(i % 2), 1 << 16,
                overlap_hidden_s=0.005, inflight_depth=2,
                active_plan="four_step+ftail", stream="beam0",
                trace_id=i + 1))
    assert TR.main([path, "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    # every section of the dict report is present in the JSON output
    assert set(doc) == set(TR.report(path))
    assert set(doc) >= {"journal", "records", "stages", "overlap",
                        "resilience", "compute", "durability",
                        "fleet", "timeline"}
    assert doc["records"] == 4
    assert doc["stages"]["dispatch"]["count"] == 4
    assert doc["fleet"]["beam0"]["records"] == 4
    # and the md rendering consumes the identical dict
    md = TR._md(doc)
    assert "## Per-stage wall clock" in md and "| beam0 |" in md


def test_gzip_rotated_generation_reads_transparently(tmp_path):
    """Satellite: a .jsonl.gz previous generation (and a torn gzip
    tail) feed the report exactly like plaintext."""
    import gzip

    from srtb_tpu.tools import telemetry_report as TR

    path = str(tmp_path / "j.jsonl")
    with gzip.open(path + ".1.gz", "wt", compresslevel=1) as f:
        for i in range(3):
            f.write(json.dumps({"type": "segment_span", "v": 7,
                                "ts": 1000.0 + i, "segment": i,
                                "stages_ms": {"sink": 1.0},
                                "samples": 1}) + "\n")
    with open(path, "w") as f:
        f.write(json.dumps({"type": "segment_span", "v": 7,
                            "ts": 1003.0, "segment": 3,
                            "stages_ms": {"sink": 1.0},
                            "samples": 1}) + "\n")
    recs = TR.load(path)
    assert [r["segment"] for r in recs] == [0, 1, 2, 3]
    # torn gzip tail (crash mid-rotation): readable prefix survives
    raw = open(path + ".1.gz", "rb").read()
    open(path + ".1.gz", "wb").write(raw[:len(raw) - 8])
    recs = TR.load(path)
    assert recs and recs[-1]["segment"] == 3


def test_timeline_tail_record_no_rate_spike(tmp_path):
    """A record landing just past a bin boundary must not divide by an
    epsilon window: the mean inter-record gap floors the final bin's
    covered time, so the reported rate stays near the true one."""
    from srtb_tpu.tools import telemetry_report as TR

    path = tmp_path / "j.jsonl"
    with open(path, "w") as f:
        for ts in (1000.0, 1010.01):
            f.write(json.dumps({"type": "segment_span", "v": 1,
                                "ts": ts, "segment": 0,
                                "stages_ms": {"sink": 1.0},
                                "samples": 1}) + "\n")
    tl = TR.timeline(TR.load(str(path)), bin_s=10.0)
    assert len(tl) == 2
    # true rate ~0.1 seg/s; the naive covered-time (0.01 s) would say 100
    assert tl[-1]["segments_per_sec"] < 0.2


# ------------------------------------------------------------- healthz


def test_healthz_staleness(tmp_path):
    from srtb_tpu.gui.server import WaterfallHTTPServer

    metrics.reset()
    srv = WaterfallHTTPServer(str(tmp_path), port=0,
                              health_stale_after_s=5.0).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        # no segment yet: idle but healthy (startup must not page)
        h = json.loads(urllib.request.urlopen(base + "/healthz").read())
        assert h["status"] == "idle" and h["ok"]
        # fresh segment: ok with a small age
        telemetry.mark_segment()
        h = json.loads(urllib.request.urlopen(base + "/healthz").read())
        assert h["status"] == "ok" and h["last_segment_age_s"] < 5.0
        # age the stamp beyond the threshold: 503 + stale
        metrics.set(telemetry.LAST_SEGMENT_MONOTONIC,
                    time.monotonic() - 60.0)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/healthz")
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["status"] == "stale"
    finally:
        srv.stop()
        metrics.reset()


def test_metrics_endpoint_serves_histograms(tmp_path):
    """/metrics speaks Prometheus including the per-stage histograms the
    pipeline feeds (acceptance: at least one histogram series with the
    stage names)."""
    from srtb_tpu.gui.server import WaterfallHTTPServer

    metrics.reset()
    metrics.histogram("stage_seconds",
                      labels={"stage": "dispatch"}).observe(0.01)
    srv = WaterfallHTTPServer(str(tmp_path), port=0).start()
    try:
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics").read().decode()
        assert "# TYPE srtb_stage_seconds histogram" in text
        assert 'srtb_stage_seconds_bucket{le="+Inf",stage="dispatch"} 1' \
            in text
    finally:
        srv.stop()
        metrics.reset()


# ---------------------------------------------------- pipeline e2e span


def test_pipeline_writes_segment_spans(tmp_path):
    """A CPU-backend synthetic run produces a journal whose spans carry
    the integrated StageTimer's per-stage wall clock, and the registry
    carries matching stage histograms + sliding-window rates."""
    from srtb_tpu.config import Config
    from srtb_tpu.io.synth import make_dispersed_baseband
    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.tools import telemetry_report as TR

    metrics.reset()
    n = 1 << 16
    data = make_dispersed_baseband(n * 2, 1405.0, 64.0, 0.0,
                                   pulse_positions=n // 2, nbits=8)
    path = str(tmp_path / "bb.bin")
    data.tofile(path)
    journal = str(tmp_path / "journal.jsonl")
    cfg = Config(
        baseband_input_count=n,
        baseband_input_bits=8,
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        input_file_path=path,
        baseband_output_file_prefix=str(tmp_path / "out_"),
        spectrum_channel_count=1 << 8,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        baseband_reserve_sample=False,
        writer_thread_count=0,
        telemetry_journal_path=journal,
    )
    with Pipeline(cfg, sinks=[]) as pipe:
        stats = pipe.run(max_segments=2)
    assert stats.segments == 2
    # integrated StageTimer: totals surface on the stats object, with
    # exactly one ingest sample per segment (the terminal failed source
    # read is not recorded)
    assert set(stats.extras["stages"]) >= {"ingest", "dispatch",
                                           "fetch", "sink"}
    assert stats.extras["stages"]["ingest"]["count"] == 2
    recs = TR.load(journal)
    assert len(recs) == 2
    for rec in recs:
        assert set(rec["stages_ms"]) == {"ingest", "dispatch",
                                         "fetch", "sink"}
        assert all(v >= 0 for v in rec["stages_ms"].values())
        assert rec["samples"] == n
    assert [r["segment"] for r in recs] == [0, 1]
    # report parses it end to end
    rep = TR.report(journal)
    assert rep["records"] == 2
    assert rep["stages"]["dispatch"]["count"] == 2
    # registry: stage histograms + windowed rates + healthz stamp
    snap = metrics.snapshot()
    assert snap["segments"] == 2
    assert snap["stage_seconds_dispatch_count"] >= 2
    assert snap["segments_per_sec_10s"] > 0
    assert metrics.get(telemetry.LAST_SEGMENT_MONOTONIC) > 0
    prom = metrics.prometheus()
    for stage in ("ingest", "dispatch", "fetch", "sink"):
        assert f'srtb_stage_seconds_count{{stage="{stage}"}}' in prom
    metrics.reset()


def test_file_reader_ingest_gauges(tmp_path):
    """The file ingest path stamps windowed read throughput and pool
    occupancy gauges (the host-side ring-occupancy analog)."""
    from srtb_tpu.config import Config
    from srtb_tpu.io.file_input import BasebandFileReader
    from srtb_tpu.utils.bufferpool import BufferPool

    metrics.reset()
    path = tmp_path / "raw.bin"
    path.write_bytes(bytes(range(256)) * 16)
    cfg = Config(baseband_input_count=1 << 10, baseband_input_bits=8,
                 input_file_path=str(path),
                 baseband_reserve_sample=False)
    reader = BasebandFileReader(cfg, buffer_pool=BufferPool("t"))
    next(reader)
    snap = metrics.snapshot()
    assert snap["file_bytes_read"] == 1 << 10
    assert snap["file_bytes_read_per_sec_10s"] > 0
    assert snap["segment_pool_in_use"] == 1
    reader.close()
    metrics.reset()
