"""End-to-end cross-check against a reference-faithful float64 oracle.

The reference's acceptance evidence is a manual run on the J1644-4559
recording (ref: README.md:9-19) — not reproducible here.  The closest
substitute: synthesize baseband bytes, run BOTH this repo's full pipeline
(file -> unpack -> R2C -> RFI s1 -> chirp -> waterfall -> SK -> detect ->
candidate files) AND an independent float64 numpy transliteration of the
reference's chain over the *identical bytes*, then require the written
.npy waterfall and .tim time series to agree to float32 tolerance.

The oracle below re-derives every stage from the reference formulas
(cited per stage) rather than calling the ops under test, so a sign/
convention/ordering error anywhere in the device chain fails the test.
"""

import numpy as np
import pytest
from oracle_utils import oracle_stream_chain, oracle_unpack

from srtb_tpu.config import Config
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.pipeline.runtime import Pipeline


def _oracle_chain(raw_bytes: np.ndarray, cfg: Config):
    """float64 transliteration of the reference device chain (shared
    per-stage oracle lives in oracle_utils, cited there)."""
    x = oracle_unpack(raw_bytes, cfg.baseband_input_bits)
    return oracle_stream_chain(x, cfg)


@pytest.fixture(scope="module")
def crosscheck_run(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("xcheck")
    n = 1 << 16
    cfg = Config(
        baseband_input_count=n,
        baseband_input_bits=2,
        baseband_format_type="simple",
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        dm=30.0,
        input_file_path=str(tmp / "bb.bin"),
        baseband_output_file_prefix=str(tmp / "out_"),
        spectrum_channel_count=1 << 6,
        signal_detect_signal_noise_threshold=5.0,
        signal_detect_max_boxcar_length=16,
        mitigate_rfi_average_method_threshold=1e9,   # strict-parity tier:
        mitigate_rfi_spectral_kurtosis_threshold=1e9,  # no threshold flips
        baseband_reserve_sample=False,
    )
    data = make_dispersed_baseband(
        n, cfg.baseband_freq_low, cfg.baseband_bandwidth, cfg.dm,
        pulse_positions=n // 2, pulse_amp=30.0, nbits=2)
    data.tofile(cfg.input_file_path)

    pipe = Pipeline(cfg)
    stats = pipe.run()
    raw = np.fromfile(cfg.input_file_path, dtype=np.uint8, count=n // 4)
    wf_o, ts_o, nzap_o = _oracle_chain(raw, cfg)
    return cfg, pipe, stats, wf_o, ts_o


def test_pipeline_detects_and_writes(crosscheck_run):
    cfg, pipe, stats, wf_o, ts_o = crosscheck_run
    assert stats.signals >= 1, "dispersed pulse must be detected"
    assert pipe.sinks[0].written


def test_waterfall_file_matches_oracle(crosscheck_run):
    """The candidate .npy on disk must equal the float64 oracle waterfall
    to f32 accuracy — full-chain numeric parity on identical bytes."""
    cfg, pipe, stats, wf_o, ts_o = crosscheck_run
    wf = np.load(pipe.sinks[0].written[0].npy_paths[0])
    assert wf.shape == wf_o.shape
    scale = np.abs(wf_o).max()
    np.testing.assert_allclose(wf, wf_o.astype(np.complex64),
                               atol=2e-4 * scale, rtol=2e-3)


def test_tim_file_matches_oracle(crosscheck_run):
    """The boxcar-1 .tim on disk must equal the oracle's mean-subtracted
    power time series."""
    cfg, pipe, stats, wf_o, ts_o = crosscheck_run
    tim_paths = [p for p in pipe.sinks[0].written[0].tim_paths
                 if p.endswith(".1.tim") or ".1.tim" in p]
    assert tim_paths, pipe.sinks[0].written[0].tim_paths
    ts = np.fromfile(tim_paths[0], dtype="<f4")
    assert ts.size == ts_o.size
    scale = np.abs(ts_o).max()
    np.testing.assert_allclose(ts, ts_o.astype(np.float32),
                               atol=2e-4 * scale, rtol=2e-3)


def test_time_series_error_bound(crosscheck_run):
    """VERDICT r4 #4: the time-series error must decompose into its two
    causes, each under its derived bound — (a) the pairwise-tree f32
    summation (ops.detect.tree_sum_freq: <= (lg K + lg T + 5) * eps *
    max raw series, deterministic, backend-independent) and (b) the
    waterfall's own f32 error propagated through |.|^2 (worst-case
    coherent Cauchy-Schwarz, no statistical assumption).  The same
    gates run at the flagship 2^30/2^15 geometry in
    tools/production_oracle.py; this pins them in CI at test scale."""
    cfg, pipe, stats, wf_o, ts_o = crosscheck_run
    wf = np.load(pipe.sinks[0].written[0].npy_paths[0])   # f32 device wf
    tim_paths = [p for p in pipe.sinks[0].written[0].tim_paths
                 if ".1.tim" in p]
    ts = np.fromfile(tim_paths[0], dtype="<f4").astype(np.float64)

    # exact f64 freq-sum of the device's f32 waterfall: the pivot
    p64 = wf.real.astype(np.float64) ** 2 + wf.imag.astype(np.float64) ** 2
    ts_pivot = p64.sum(axis=0)
    ts_raw_max = float(ts_pivot.max())
    ts_pivot -= ts_pivot.mean()

    from srtb_tpu.ops.detect import time_series_error_gates
    k_ch, t_len = wf.shape
    wf_err = np.abs(wf.astype(np.complex128) - wf_o).max()
    ts_sum_gate, ts_prop_gate = time_series_error_gates(
        k_ch, t_len, ts_raw_max, wf_err)
    ts_sum_err = np.abs(ts - ts_pivot).max()
    assert ts_sum_err <= ts_sum_gate, (ts_sum_err, ts_sum_gate)
    ts_prop_err = np.abs(ts_pivot - ts_o).max()
    assert ts_prop_err <= ts_prop_gate, (ts_prop_err, ts_prop_gate)

    # and the total is explained by the two causes together
    total = np.abs(ts - ts_o).max()
    assert total <= ts_sum_gate + ts_prop_gate, \
        (total, ts_sum_gate, ts_prop_gate)


def test_rfi_decision_parity_with_injected_tone():
    """Decision-parity tier: a strong injected CW tone must produce the
    SAME stage-1 zap set and SK row-zap count in the pipeline as in the
    float64 oracle (threshold decisions, not just values)."""
    n = 1 << 14
    cfg = Config(
        baseband_input_count=n,
        baseband_input_bits=2,
        baseband_format_type="simple",
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        dm=0.0,
        spectrum_channel_count=1 << 5,
        signal_detect_signal_noise_threshold=50.0,
        signal_detect_max_boxcar_length=8,
        mitigate_rfi_average_method_threshold=20.0,
        mitigate_rfi_spectral_kurtosis_threshold=1.3,
        baseband_reserve_sample=False,
    )
    rng = np.random.default_rng(11)
    t = np.arange(n, dtype=np.float64)
    tone = 1.2 * np.sin(2 * np.pi * 0.1357 * t)   # strong narrowband RFI
    sig = rng.normal(0, 0.35, size=n) + tone
    q = np.clip(np.round(sig + 1.5), 0, 3).astype(np.uint8)  # 2-bit quant
    raw = (q[0::4] << 6) | (q[1::4] << 4) | (q[2::4] << 2) | q[3::4]

    from srtb_tpu.pipeline.segment import SegmentProcessor, \
        waterfall_to_numpy
    proc = SegmentProcessor(cfg)
    wf_ri, res = proc.process(raw)
    wf = waterfall_to_numpy(wf_ri)[0]
    wf_o, ts_o, nzap_o = _oracle_chain(raw, cfg)

    zapped_rows = int((np.abs(wf[:, 0]) == 0).sum())
    zapped_rows_o = int((np.abs(wf_o[:, 0]) == 0).sum())
    assert zapped_rows == zapped_rows_o, (zapped_rows, zapped_rows_o)
    assert zapped_rows >= 1  # the tone really tripped something
    assert int(np.asarray(res.zero_count)[0]) == zapped_rows_o


@pytest.mark.parametrize("strategy", ["four_step", "mxu", "pallas"])
def test_alternate_fft_backends_match_oracle(crosscheck_run, strategy):
    """Every FFT backend (not just the default monolithic XLA op) must
    reproduce the reference-transliteration oracle's waterfall: the
    four-step decomposition and the MXU DFT-matmul path go through the
    same pack + Hermitian post-process, so this pins their conventions
    (unnormalized, drop-Nyquist, frequency-major) to the oracle too."""
    cfg, _, _, wf_o, _ = crosscheck_run
    from srtb_tpu.pipeline.segment import SegmentProcessor, \
        waterfall_to_numpy
    proc = SegmentProcessor(cfg.replace(fft_strategy=strategy))
    raw = np.fromfile(cfg.input_file_path, dtype=np.uint8,
                      count=cfg.baseband_input_count // 4)
    wf = waterfall_to_numpy(proc.process(raw)[0])[0]
    scale = np.abs(wf_o).max()
    np.testing.assert_allclose(wf, wf_o, atol=2e-4 * scale, rtol=0)


def test_production_geometry_oracle_slice(tmp_path):
    """Round-3 verdict #8: the f64 crosscheck at the REAL flagship
    geometry (2^30 samples / 2^15 channels / DM -478.80, staged plan).
    Hours + ~60 GB on CPU, so gated: SRTB_TEST_SLOW=1 runs it here; the
    committed artifact (artifacts/production_oracle.json, produced by
    srtb_tpu.tools.production_oracle) pins the numbers otherwise."""
    import json
    import os

    from srtb_tpu.tools import production_oracle

    if not os.environ.get("SRTB_TEST_SLOW"):
        art = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "artifacts",
            "production_oracle.json")
        if not os.path.exists(art):
            pytest.skip("slow (SRTB_TEST_SLOW=1) and no committed artifact")
        rec = json.load(open(art))
        assert rec["ok"], rec
        assert rec["log2n"] >= 30 and rec["channels"] >= (1 << 15), rec
        return
    out = tmp_path / "production_oracle.json"
    rc = production_oracle.main(["--log2n", "30", "--log2chan", "15",
                                 "--out", str(out)])
    assert rc == 0, json.load(open(out))
