"""Fused two-pass Pallas four-step C2C (ops/pallas_fft2) vs numpy.

CPU CI runs interpret mode at the smallest supported size (m = 2^24 —
the module deliberately only covers the segment sizes where monolithic
XLA falters); SRTB_TEST_TPU=1 lowers the same cases through Mosaic.
The tolerance is looser than the single-level row kernel's: the value
passes through four bf16x3 DFT-matmul levels plus two twiddle stages.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from srtb_tpu.ops import fft as F
from srtb_tpu.ops import pallas_fft2 as PF2

ON_TPU = jax.default_backend() in ("tpu", "axon")
INTERPRET = not ON_TPU

M = 1 << 24  # smallest pallas2 size (n1=4096, n2=4096)


def _rand_c64(shape, seed):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape)
            + 1j * rng.standard_normal(shape)).astype(np.complex64)


def test_factorization_window():
    assert PF2._factor(M) == (4096, 4096)
    assert PF2._factor(1 << 26) == (4096, 1 << 14)
    assert PF2._factor(1 << 29) == (8192, 1 << 16)
    assert not PF2.supported(1 << 23)   # below the window
    assert not PF2.supported(1 << 30)   # above the window
    assert not PF2.supported(3 * (1 << 22))  # not a power of two


@pytest.mark.parametrize("inverse", [False, True])
def test_fft2_matches_numpy(inverse):
    x = _rand_c64(M, 7 + inverse)
    want = (np.fft.ifft(x.astype(np.complex128), norm="forward") if inverse
            else np.fft.fft(x.astype(np.complex128)))
    got = np.asarray(PF2.fft2_c2c(jnp.asarray(x), inverse=inverse,
                                  interpret=INTERPRET))
    scale = np.abs(want).max()
    assert np.abs(got - want).max() / scale < 2e-5


# (the pass-1 row spelling and the rows-helper A/B knobs were retired in
# round 5: real Mosaic rejects their in-kernel minor-lb reshapes, so the
# column-native pass 1 + the single vmem_fft_rows spelling are the one
# lowering — covered by every other oracle test in this file)


def test_fft2_blocked_output_unblocks():
    x = _rand_c64(M, 3)
    want = np.fft.fft(x.astype(np.complex128))
    raw = PF2.fft2_c2c(jnp.asarray(x), natural=False, interpret=INTERPRET)
    got = np.asarray(PF2.unblock(raw, M))
    assert np.abs(got - want).max() / np.abs(want).max() < 2e-5


def test_fft2_leading_dims():
    x = _rand_c64((2, M), 5)
    want = np.fft.fft(x.astype(np.complex128))
    got = np.asarray(PF2.fft2_c2c(jnp.asarray(x), interpret=INTERPRET))
    assert got.shape == x.shape
    assert np.abs(got - want).max() / np.abs(want).max() < 2e-5


def test_segment_rfft_pallas2_strategy():
    """End-to-end R2C through the pallas2 strategy (pack + two-pass C2C +
    Hermitian post) against the monolithic rfft at n = 2^25."""
    n = 2 * M
    rng = np.random.default_rng(11)
    x = rng.standard_normal(n).astype(np.float32)
    want = np.fft.rfft(x.astype(np.float64))[:-1]
    got = np.asarray(F.segment_rfft(
        jnp.asarray(x), "pallas2_interpret" if INTERPRET else "pallas2"))
    assert np.abs(got - want).max() / np.abs(want).max() < 2e-5


def test_rfft_subbyte_pallas2_blocked_planes():
    """The blocked-plane sub-byte R2C with pallas2 plane FFTs (the
    production 2^30 ingest composition) against the f64 oracle: 4-bit
    (count=2, one packed plane of length n/2 = 2^24)."""
    from srtb_tpu.ops import unpack as U

    n = 2 * M
    rng = np.random.default_rng(17)
    raw = rng.integers(0, 256, n // 2, dtype=np.uint8)
    x = np.asarray(U.unpack(jnp.asarray(raw), 4, None)).astype(np.float64)
    want = np.fft.rfft(x)[:-1]
    got = np.asarray(F.rfft_subbyte(
        jnp.asarray(raw), 4,
        "pallas2_interpret" if INTERPRET else "pallas2"))
    assert np.abs(got - want).max() / np.abs(want).max() < 2e-5


def test_segment_rfft_pallas2_small_falls_back():
    """Below the pallas2 window the strategy silently takes the
    pallas-legs four-step — tiny configs must not crash."""
    n = 1 << 16
    rng = np.random.default_rng(13)
    x = rng.standard_normal(n).astype(np.float32)
    want = np.fft.rfft(x.astype(np.float64))[:-1]
    got = np.asarray(F.segment_rfft(
        jnp.asarray(x), "pallas2_interpret" if INTERPRET else "pallas2"))
    assert np.abs(got - want).max() / np.abs(want).max() < 5e-6


def test_fourstep_twiddle_precision_at_window_edge():
    """The in-kernel hi/lo phase split must stay accurate at the top of
    the window (m = 2^29, residues up to 2^29 — far beyond f32's 24-bit
    mantissa), where an end-to-end CPU-interpret test is impractical.
    Checked against float64 on the worst blocks: the highest j2 rows
    (largest residues) and a mid-spectrum block."""
    m = 1 << 29
    n1, n2 = PF2._factor(m)
    for j2_0 in (n2 - 8, n2 // 2):
        wr, wi = jax.jit(
            lambda j0: PF2._fourstep_twiddle_t(n1, 8, m, -1.0, j0),
            static_argnums=0)(j2_0)
        k1 = np.arange(n1)[:, None]
        d = np.arange(8)[None, :] + j2_0
        want = np.exp(-2j * np.pi * (d * k1).astype(np.float64) / m)
        err = np.abs((np.asarray(wr) + 1j * np.asarray(wi)) - want).max()
        assert err < 2e-6, (j2_0, err)


def test_fft2_asymmetric_factorization():
    """m = 2^25 factors 4096 x 8192 (n2 != n1, lb2=64) — the asymmetric
    shape every production size [2^25, 2^29] uses; the symmetric
    m = 2^24 tests alone would never exercise distinct leg lengths or
    the rectangular four-step twiddle."""
    m = 1 << 25
    assert PF2._factor(m) == (4096, 8192)
    x = _rand_c64(m, 41)
    want = np.fft.fft(x.astype(np.complex128))
    got = np.asarray(PF2.fft2_c2c(jnp.asarray(x), interpret=INTERPRET))
    assert np.abs(got - want).max() / np.abs(want).max() < 2e-5


def test_block_sizing_budgets_padded_footprint(monkeypatch):
    """Round-3 advisor catch: blocks must be sized from the PADDED VMEM
    footprint (bb < 128 lane-pads to 128 across 2x-pipelined in/out
    refs), not logical f32 words.  Pins: lane-dense pass-1 blocks at
    every supported factorization, the modeled footprint staying inside
    the budget, and the absolute env overrides surviving."""
    monkeypatch.delenv("SRTB_PALLAS2_BB", raising=False)
    monkeypatch.delenv("SRTB_PALLAS2_RB", raising=False)
    monkeypatch.delenv("SRTB_PALLAS2_VMEM_MB", raising=False)
    budget = PF2._vmem_budget()
    for log2m in range(24, 30):
        n1, n2 = PF2._factor(1 << log2m)
        bb = PF2._block_cols(n1, n2)
        rb = PF2._block_rows(n2, n1)
        assert bb >= 128 and n2 % bb == 0, (log2m, bb)
        assert rb >= 8 and n1 % rb == 0, (log2m, rb)
        assert PF2._pass1_bytes(n1, bb) <= budget, log2m
        assert PF2._pass2_bytes(n2, rb) <= budget, log2m
    # refs alone at the padded minimum exceed a 16 MiB-era budget: the
    # floor is returned (a vmem_limit question, not a sizing one)
    monkeypatch.setenv("SRTB_PALLAS2_VMEM_MB", "14")
    assert PF2._block_cols(8192, 1 << 16) == 128
    monkeypatch.setenv("SRTB_PALLAS2_BB", "64")
    monkeypatch.setenv("SRTB_PALLAS2_RB", "16")
    assert PF2._block_cols(4096, 4096) == 64
    assert PF2._block_rows(4096, 4096) == 16
