"""Checkpoint/resume tests: a restarted file-mode pipeline continues from
the recorded logical offset and produces the same total segment coverage
as an uninterrupted run."""

import os

import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.pipeline.checkpoint import StreamCheckpoint
from srtb_tpu.pipeline.runtime import Pipeline


def _cfg(tmp_path, n=1 << 12):
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=4 * n, dtype=np.uint8)
    path = str(tmp_path / "in.bin")
    data.tofile(path)
    return Config(
        baseband_input_count=n,
        baseband_input_bits=8,
        input_file_path=path,
        baseband_output_file_prefix=str(tmp_path / "out_"),
        spectrum_channel_count=1 << 4,
        signal_detect_max_boxcar_length=8,
        signal_detect_signal_noise_threshold=99.0,  # never trigger
        baseband_reserve_sample=False,
        checkpoint_path=str(tmp_path / "ckpt.json"),
    )


def test_checkpoint_file_roundtrip(tmp_path):
    p = str(tmp_path / "s.json")
    ck = StreamCheckpoint(p)
    assert ck.segments_done == 0
    ck.update(3, 12345)
    ck2 = StreamCheckpoint(p)
    assert ck2.segments_done == 3
    assert ck2.file_offset_bytes == 12345
    ck2.clear()
    assert not os.path.exists(p)


def test_pipeline_resume(tmp_path):
    cfg = _cfg(tmp_path)
    # run only 2 of the 4 segments, then "crash"
    pipe1 = Pipeline(cfg)
    pipe1.run(max_segments=2)
    ck = StreamCheckpoint(cfg.checkpoint_path)
    assert ck.segments_done == 2
    assert ck.file_offset_bytes == 2 * cfg.baseband_input_count

    # resume: should process exactly the remaining 2 segments
    pipe2 = Pipeline(cfg)
    stats = pipe2.run()
    assert stats.segments == 2
    ck = StreamCheckpoint(cfg.checkpoint_path)
    assert ck.segments_done == 4
    assert ck.file_offset_bytes == 4 * cfg.baseband_input_count
