"""Shared float64 oracle: an independent numpy transliteration of the
reference device chain, used by the crosscheck tests.

Every function here re-derives the reference formulas from the cited
C++ sources rather than calling the ops under test, so a sign /
convention / interleave error anywhere in the device chain fails the
crosscheck instead of cancelling out.
"""

import numpy as np

from srtb_tpu.ops import dedisperse as dd
from srtb_tpu.ops import rfi

D = 4.148808e3  # MHz^2 pc^-1 cm^3 s (ref: coherent_dedispersion.hpp:67)


def oracle_unpack(raw_bytes: np.ndarray, nbits: int) -> np.ndarray:
    """Single-stream unpack in float64 (ref: unpack.hpp:43-140):
    1/2/4-bit unsigned fields MSB-first within each byte; 8 unsigned,
    -8 signed int8."""
    b = np.asarray(raw_bytes, dtype=np.uint8)
    if nbits in (1, 2, 4):
        count = 8 // nbits
        mask = (1 << nbits) - 1
        fields = [(b.astype(np.uint16) >> ((count - 1 - i) * nbits)) & mask
                  for i in range(count)]
        return np.stack(fields, axis=-1).reshape(-1).astype(np.float64)
    if nbits == 8:
        return b.astype(np.float64)
    if nbits == -8:
        return b.view(np.int8).astype(np.float64)
    raise ValueError(f"oracle_unpack: unsupported nbits {nbits}")


def oracle_deinterleave(raw_bytes: np.ndarray, fmt_name: str,
                        nbits: int) -> list[np.ndarray]:
    """De-interleave a raw byte segment into per-stream float64 samples,
    transliterated from the reference unpack kernels:

    - ``simple``                 1 stream, plain unpack
    - ``interleaved_samples_2``  "1212" byte-interleave
      (ref: unpack.hpp:214-244)
    - ``naocpsr_snap1``          "1122" pair-interleave, int8
      (ref: unpack.hpp:253-283)
    - ``gznupsr_a1_v1``          4-way word-interleave (4 samples per
      stream per 16-byte group), uint8 XOR 0x80 -> int8
      (ref: unpack.hpp:291-328)
    - ``gznupsr_a1``             2-way word-interleave, int8, no XOR
      (ref: unpack.hpp:336-369)
    """
    b = np.asarray(raw_bytes, dtype=np.uint8)
    if fmt_name == "simple":
        return [oracle_unpack(b, nbits)]
    if fmt_name == "interleaved_samples_2":
        x = b.reshape(-1, 2)
        return [oracle_unpack(x[:, i].copy(), nbits) for i in range(2)]
    if fmt_name == "naocpsr_snap1":
        x = b.reshape(-1, 4)
        return [oracle_unpack(x[:, 0:2].reshape(-1), -8),
                oracle_unpack(x[:, 2:4].reshape(-1), -8)]
    if fmt_name == "gznupsr_a1_v1":
        x = (b.reshape(-1, 4, 4) ^ np.uint8(0x80)).view(np.int8)
        return [x[:, i, :].reshape(-1).astype(np.float64) for i in range(4)]
    if fmt_name == "gznupsr_a1":
        x = b.reshape(-1, 2, 4).view(np.int8)
        return [x[:, i, :].reshape(-1).astype(np.float64) for i in range(2)]
    raise ValueError(f"oracle_deinterleave: unknown format {fmt_name}")


def oracle_stream_chain(x: np.ndarray, cfg):
    """float64 transliteration of the reference device chain over one
    stream of already-unpacked samples.  Returns (waterfall, time series,
    SK-zapped row count)."""
    n = x.size
    n_spec = n // 2

    # R2C, Nyquist dropped (ref: fft_pipe.hpp:44-78)
    spec = np.fft.rfft(x)[:-1]

    # RFI stage 1: zap > threshold*mean power, normalize survivors by
    # (N^2/channels)^-0.5 evaluated in f32 (ref: rfi_mitigation_pipe.hpp:50-80)
    power = spec.real**2 + spec.imag**2
    zap1 = power > cfg.mitigate_rfi_average_method_threshold * power.mean()
    coeff = rfi.normalization_coefficient(n_spec, cfg.spectrum_channel_count)
    spec = np.where(zap1, 0.0, spec * coeff)

    # coherent dedispersion chirp (ref: coherent_dedispersion.hpp:133-150,
    # Jiang 2022): k = D*1e6*dm/f*((f-f_c)/f_c)^2, phase = -2*pi*frac(k)
    f_min, f_c, df = dd.spectrum_frequencies(cfg, n_spec)
    f = f_min + df * np.arange(n_spec, dtype=np.float64)
    k = D * 1e6 * cfg.dm / f * ((f - f_c) / f_c) ** 2
    chirp = np.exp(-2j * np.pi * np.modf(k)[0])
    spec = spec * chirp

    # waterfall: [channels, wlen] rows, unnormalized backward C2C
    # (ref: fft_pipe.hpp:285-344)
    ch = min(cfg.spectrum_channel_count, n_spec)
    wlen = n_spec // ch
    wf = np.fft.ifft(spec.reshape(ch, wlen), axis=-1) * wlen

    # SK stage 2 (ref: rfi_mitigation.hpp:290-341), thresholds in f32 as
    # the implementation computes them
    lo, hi = rfi.sk_decision_thresholds(
        wlen, cfg.mitigate_rfi_spectral_kurtosis_threshold)
    p = wf.real**2 + wf.imag**2
    s2, s4 = p.sum(axis=-1), (p * p).sum(axis=-1)
    sk = wlen * s4 / (s2 * s2)
    zap2 = (sk > hi) | (sk < lo)
    wf = np.where(zap2[:, None], 0.0, wf)

    # detect: power time series over the untrimmed window, mean-subtracted
    # (ref: signal_detect_pipe.hpp:305-334; reserve disabled in this cfg)
    ts = (wf.real**2 + wf.imag**2).sum(axis=0)
    ts = ts - ts.mean()
    return wf, ts, int(zap2.sum())
