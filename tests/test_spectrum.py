"""Spectrum simplification tests (oracle: resample_oracle transliterates the
v1 kernel, ref: spectrum/simplify_spectrum.hpp:137-230; pixmap colors
config.hpp:60-68)."""

import jax.numpy as jnp
import numpy as np

from srtb_tpu.ops import spectrum as sp


def test_resample_matches_oracle():
    rng = np.random.default_rng(11)
    in_h, in_w, out_h, out_w = 37, 53, 9, 16
    power = rng.random((in_h, in_w)).astype(np.float32)
    w_f = sp.freq_area_weights(in_h, out_h)
    w_t = sp.time_interp_weights(in_w, out_w)
    got = np.asarray(sp.resample_spectrum(jnp.asarray(power),
                                          jnp.asarray(w_f),
                                          jnp.asarray(w_t)))
    expected = sp.resample_oracle(power.astype(np.float64), out_h, out_w)
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-4)


def test_resample_conserves_area():
    """Each output row sums input rows with total weight in_h/out_h."""
    in_h, out_h = 64, 10
    w_f = sp.freq_area_weights(in_h, out_h)
    np.testing.assert_allclose(w_f.sum(axis=1), in_h / out_h, rtol=1e-5)
    in_w, out_w = 64, 10
    w_t = sp.time_interp_weights(in_w, out_w)
    np.testing.assert_allclose(w_t.sum(axis=0), 1.0, rtol=1e-5)


def test_normalize_by_average():
    x = jnp.asarray(np.full((4, 4), 3.0, dtype=np.float32))
    out = np.asarray(sp.normalize_by_average(x))
    np.testing.assert_allclose(out, 0.5, rtol=1e-6)
    zero = jnp.zeros((4, 4), dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(sp.normalize_by_average(zero)),
                                  0.0)


def test_pixmap_colors():
    intensity = jnp.asarray(np.array([0.0, 1.0, 2.0, -0.5], dtype=np.float32))
    out = np.asarray(sp.generate_pixmap(intensity))
    assert out[0] == sp.COLOR_0
    assert out[1] == sp.COLOR_1
    assert out[2] == sp.COLOR_OVERFLOW
    assert out[3] == sp.COLOR_OVERFLOW


def test_pixmap_lerp_midpoint():
    out = int(np.asarray(sp.generate_pixmap(
        jnp.asarray(np.array([0.5], dtype=np.float32))))[0])
    for shift in (24, 16, 8, 0):
        c0 = (sp.COLOR_0 >> shift) & 0xFF
        c1 = (sp.COLOR_1 >> shift) & 0xFF
        got = (out >> shift) & 0xFF
        assert abs(got - (c0 + c1) / 2) <= 1
