"""Pallas row-FFT kernel (ops/pallas_fft) vs numpy oracles.

CPU CI runs interpret mode; on a real TPU (SRTB_TEST_TPU=1) the same
cases lower through Mosaic (layouts/tiling differ from interpret — the
round-1 lesson is that only a hardware run proves a Pallas kernel).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from srtb_tpu.ops import pallas_fft as PF

ON_TPU = jax.default_backend() in ("tpu", "axon")
INTERPRET = not ON_TPU


@pytest.mark.parametrize("batch,length", [(16, 1 << 13), (4, 1 << 15),
                                          (2, 1 << 16)])
@pytest.mark.parametrize("inverse", [False, True])
def test_fft_rows_matches_numpy(batch, length, inverse):
    rng = np.random.default_rng(length + inverse)
    x = (rng.standard_normal((batch, length))
         + 1j * rng.standard_normal((batch, length))).astype(np.complex64)
    want = (np.fft.ifft(x, norm="forward") if inverse
            else np.fft.fft(x.astype(np.complex128)))
    got = np.asarray(PF.fft_rows(jnp.asarray(x), inverse=inverse,
                                 interpret=INTERPRET))
    scale = np.abs(want).max()
    assert np.abs(got - want).max() / scale < 5e-6


def test_fft_rows_leading_dims_and_support():
    rng = np.random.default_rng(0)
    x = (rng.standard_normal((2, 3, 1 << 13))
         + 1j * rng.standard_normal((2, 3, 1 << 13))).astype(np.complex64)
    got = np.asarray(PF.fft_rows(jnp.asarray(x), interpret=INTERPRET))
    want = np.fft.fft(x)
    assert np.abs(got - want).max() / np.abs(want).max() < 5e-6
    assert not PF.supported(1 << 11, 4)   # below the supported range
    assert not PF.supported(3 * 1024, 4)  # not a power of two
    assert PF.supported(1 << 16, 1)


def test_fft_rows_matches_waterfall_convention():
    """The waterfall backward C2C convention (unnormalized inverse,
    ops.fft.c2c_backward) must be reproduced exactly by inverse mode."""
    from srtb_tpu.ops import fft as F

    rng = np.random.default_rng(1)
    x = (rng.standard_normal((4, 1 << 13))
         + 1j * rng.standard_normal((4, 1 << 13))).astype(np.complex64)
    want = np.asarray(F.c2c_backward(jnp.asarray(x)))
    got = np.asarray(PF.fft_rows(jnp.asarray(x), inverse=True,
                                 interpret=INTERPRET))
    assert np.abs(got - want).max() / np.abs(want).max() < 5e-6


def test_pallas_waterfall_in_pipeline_matches_jnp():
    """use_pallas with a supported watfft length takes the Pallas row-FFT
    waterfall branch (pipeline/segment._spectrum_tail); output must match
    the XLA waterfall path."""
    from srtb_tpu.config import Config
    from srtb_tpu.pipeline.segment import SegmentProcessor

    n = 1 << 16  # n_spectrum 2^15, 4 channels -> watfft_len 2^13
    rng = np.random.default_rng(3)
    raw = rng.integers(0, 256, size=n // 4, dtype=np.uint8)
    base = dict(
        baseband_input_count=n, baseband_input_bits=2,
        baseband_format_type="simple", baseband_freq_low=1405.0,
        baseband_bandwidth=64.0, baseband_sample_rate=128e6, dm=5.0,
        spectrum_channel_count=4,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        signal_detect_max_boxcar_length=16,
        baseband_reserve_sample=False)
    ref = SegmentProcessor(Config(**base))
    pal = SegmentProcessor(Config(use_pallas=True, **base))
    fused = SegmentProcessor(Config(use_pallas=True, use_pallas_sk=True,
                                    **base))
    assert PF.supported(pal.watfft_len, pal.channel_count)
    wf_a, res_a = ref.process(raw)
    wf_a = np.asarray(wf_a)
    scale = np.abs(wf_a).max()
    for name, proc in (("wf", pal), ("wf+sk", fused)):
        wf_b, res_b = proc.process(raw)
        np.testing.assert_allclose(np.asarray(wf_b), wf_a,
                                   atol=5e-3 * scale, rtol=0,
                                   err_msg=name)
        assert np.array_equal(np.asarray(res_a.signal_counts),
                              np.asarray(res_b.signal_counts)), name
        assert np.array_equal(np.asarray(res_a.zero_count),
                              np.asarray(res_b.zero_count)), name


def test_pallas_fft_strategy_matches_monolithic():
    """fft_strategy='pallas' (four-step with Pallas row legs) through the
    full segment processor must match the monolithic XLA path."""
    from srtb_tpu.config import Config
    from srtb_tpu.pipeline.segment import SegmentProcessor

    n = 1 << 16
    rng = np.random.default_rng(5)
    raw = rng.integers(0, 256, size=n // 4, dtype=np.uint8)
    base = dict(
        baseband_input_count=n, baseband_input_bits=2,
        baseband_format_type="simple", baseband_freq_low=1405.0,
        baseband_bandwidth=64.0, baseband_sample_rate=128e6, dm=5.0,
        spectrum_channel_count=8,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        signal_detect_max_boxcar_length=16,
        baseband_reserve_sample=False)
    ref = SegmentProcessor(Config(fft_strategy="monolithic", **base))
    pal = SegmentProcessor(Config(fft_strategy="pallas", **base))
    wf_a, res_a = ref.process(raw)
    wf_b, res_b = pal.process(raw)
    wf_a, wf_b = np.asarray(wf_a), np.asarray(wf_b)
    scale = np.abs(wf_a).max()
    np.testing.assert_allclose(wf_b, wf_a, atol=5e-3 * scale, rtol=0)
    assert np.array_equal(np.asarray(res_a.signal_counts),
                          np.asarray(res_b.signal_counts))


@pytest.mark.parametrize("length", [1 << 12, 1 << 13])
def test_fft_rows_small_lengths(length):
    rng = np.random.default_rng(length)
    x = (rng.standard_normal((8, length))
         + 1j * rng.standard_normal((8, length))).astype(np.complex64)
    got = np.asarray(PF.fft_rows(jnp.asarray(x), interpret=INTERPRET))
    want = np.fft.fft(x.astype(np.complex128))
    assert np.abs(got - want).max() / np.abs(want).max() < 5e-6


def test_fft_rows_stats_matches_jnp():
    """fft_rows_stats_ri: inverse FFT + de-window + power moments must
    match the jnp sequence (c2c_backward -> divide -> |x|^2 sums)."""
    from srtb_tpu.ops import fft as F

    rng = np.random.default_rng(11)
    B, L = 6, 1 << 13
    x = (rng.standard_normal((B, L))
         + 1j * rng.standard_normal((B, L))).astype(np.complex64)
    dewin = (0.5 + rng.random(L)).astype(np.float32)
    wr, wi, s2p, s4p = PF.fft_rows_stats_ri(
        jnp.asarray(x.real), jnp.asarray(x.imag), inverse=True,
        dewindow=jnp.asarray(dewin), interpret=INTERPRET)
    want = np.asarray(F.c2c_backward(jnp.asarray(x))) / dewin
    got = np.asarray(wr) + 1j * np.asarray(wi)
    scale = np.abs(want).max()
    assert np.abs(got - want).max() < 5e-5 * scale
    p = np.abs(want) ** 2
    np.testing.assert_allclose(np.asarray(s2p).sum(-1), p.sum(-1),
                               rtol=1e-3)
    np.testing.assert_allclose(np.asarray(s4p).sum(-1), (p * p).sum(-1),
                               rtol=1e-3)


def test_fft_rows_stats_no_dewindow():
    """The stats variant without a de-window vector (the placeholder-tile
    branch) is the same transform as the plain inverse FFT, with correct
    finished moment sums regardless of the partials' lane grouping."""
    import numpy as np

    rng = np.random.default_rng(77)
    x = (rng.standard_normal((8, 1 << 13))
         + 1j * rng.standard_normal((8, 1 << 13))).astype(np.complex64)
    re, im, s2, s4 = PF.fft_rows_stats_ri(
        jnp.real(jnp.asarray(x)), jnp.imag(jnp.asarray(x)),
        inverse=True, interpret=INTERPRET)
    want = np.asarray(jnp.fft.ifft(x, norm="forward"))
    got2 = np.asarray(re) + 1j * np.asarray(im)
    assert np.abs(got2 - want).max() / np.abs(want).max() < 5e-6
    p = np.abs(got2) ** 2
    np.testing.assert_allclose(np.asarray(s2).sum(-1), p.sum(-1),
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(s4).sum(-1), (p * p).sum(-1),
                               rtol=1e-4)


def test_row_block_vmem_budget_knob(monkeypatch):
    """SRTB_PALLAS_VMEM_MB scales the row-block plan for hardware A/B;
    unset keeps the proven 1 MB-plane default bit-identical."""
    from srtb_tpu.ops import pallas_fft as PF

    monkeypatch.delenv("SRTB_PALLAS_VMEM_MB", raising=False)
    base = PF._row_block(1 << 14, 1 << 11)      # 2^18/2^14 = 16 rows
    assert base == 16
    # unset: the block plan keeps the proven default, but the Mosaic
    # scoped-vmem limit is ALWAYS set (100 MiB; the compiler default is
    # far below the v5e's 128 MiB and the L=2^16 leg overflows it)
    kw0 = PF._call_kwargs(interpret=False)
    assert kw0["compiler_params"].vmem_limit_bytes == 100 << 20
    monkeypatch.setenv("SRTB_PALLAS_VMEM_MB", "56")
    big = PF._row_block(1 << 14, 1 << 11)
    assert big > base and (1 << 11) % big == 0
    kw = PF._call_kwargs(interpret=False)
    assert kw["compiler_params"].vmem_limit_bytes == 56 << 20
    assert PF._call_kwargs(interpret=True) == {}
    # padded accounting: the helper's lb<128 stage/output padding must
    # shrink the block on the small-length end (lb=32 pads 4x)
    for length in (1 << 12, 1 << 13, 1 << 16):
        rows = PF._rows_budget_padded(length, 56 << 20)
        la, lb = PF._split_la_lb(length)
        plb = max(lb, 128)
        refs = 2 * 2 * rows * (length + la * plb) * 4
        live = 6 * la * rows * plb * 4
        assert refs + live <= 56 << 20, (length, rows)
    # degenerate values fail loudly and identically for both readers
    monkeypatch.setenv("SRTB_PALLAS_VMEM_MB", "0")
    with pytest.raises(ValueError):
        PF._row_block(1 << 14, 1 << 11)
    with pytest.raises(ValueError):
        PF._call_kwargs(interpret=False)
