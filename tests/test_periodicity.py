"""Periodicity/folding search mode (ops/periodicity.py +
pipeline/periodicity.py): harmonic-summed power-spectrum search +
phase folding over the dedispersed time series, landing as a
registered plan family."""

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.ops import periodicity as P
from srtb_tpu.pipeline import registry
from srtb_tpu.pipeline.periodicity import (PeriodicityResult,
                                           PeriodicitySegmentProcessor)
from srtb_tpu.pipeline.runtime import has_signal
from srtb_tpu.pipeline.segment import SegmentProcessor

N = 1 << 14
CHANNELS = 64


def _cfg(**kw):
    base = dict(
        baseband_input_count=N, baseband_input_bits=8,
        baseband_freq_low=1405.0, baseband_bandwidth=64.0,
        baseband_sample_rate=128e6, dm=0.0,
        spectrum_channel_count=CHANNELS,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        signal_detect_signal_noise_threshold=6.0,
        signal_detect_max_boxcar_length=8,
        baseband_reserve_sample=False, fft_strategy="four_step")
    base.update(kw)
    return Config(**base)


# ------------------------------------------------------------------
# ops vs the numpy oracle


def test_harmonic_levels():
    assert P.harmonic_levels(1) == (1,)
    assert P.harmonic_levels(8) == (1, 2, 4, 8)
    assert P.harmonic_levels(6) == (1, 2, 4)


def test_candidate_search_matches_oracle():
    rng = np.random.default_rng(0)
    ts = rng.standard_normal(512).astype(np.float32)
    ts += 3.0 * np.sin(2 * np.pi * 17 * np.arange(512) / 512) \
        .astype(np.float32)
    ts -= ts.mean()
    got = P.periodicity_search(ts, 8, 4, 32, min_bin=2)
    o_bins, o_snr, o_harm, o_prof = P.periodicity_oracle(
        ts, 8, 4, 32, min_bin=2)
    np.testing.assert_array_equal(np.asarray(got.bins), o_bins)
    np.testing.assert_allclose(np.asarray(got.snr), o_snr, rtol=2e-5)
    np.testing.assert_array_equal(np.asarray(got.harmonics), o_harm)
    np.testing.assert_allclose(np.asarray(got.profiles), o_prof,
                               rtol=1e-5, atol=1e-5)


def test_sinusoid_found_at_its_bin():
    rng = np.random.default_rng(1)
    t = 1024
    ts = 0.3 * rng.standard_normal(t).astype(np.float32)
    ts += np.sin(2 * np.pi * 37 * np.arange(t) / t).astype(np.float32)
    ts -= ts.mean()
    got = P.periodicity_search(ts, 8, 4, 64)
    assert int(np.asarray(got.bins)[0]) == 37
    assert float(np.asarray(got.snr)[0]) > 10.0


def test_pulse_train_candidates_are_comb_teeth():
    """A delta train's power lives on the comb at multiples of the
    fundamental: every returned candidate must sit on a tooth, and
    the folded profile at the top one concentrates the pulse."""
    t = 1024
    period = 64  # -> fundamental bin 16
    ts = np.zeros(t, np.float32)
    ts[::period] = 10.0
    rng = np.random.default_rng(2)
    ts += 0.1 * rng.standard_normal(t).astype(np.float32)
    ts -= ts.mean()
    got = P.periodicity_search(ts, 8, 4, 64)
    fundamental = t // period
    for b in np.asarray(got.bins):
        assert int(b) % fundamental == 0, np.asarray(got.bins)
    assert float(np.asarray(got.snr)[0]) > 3.0
    prof = np.asarray(P.fold(ts, np.asarray(got.bins)[0], 64))
    assert prof.max() > 5 * np.median(np.abs(prof))


def test_weak_harmonics_win_by_summing():
    """Harmonics individually near the noise floor: the summed level
    must beat level 1 (the reason the harmonic ladder exists), and
    the winning candidate is the fundamental with harmonics > 1."""
    t = 1024
    rng = np.random.default_rng(5)
    ts = rng.standard_normal(t).astype(np.float32)
    for h in (1, 2, 4, 8):
        ts += 0.17 * np.sin(
            2 * np.pi * 20 * h * np.arange(t) / t + 0.3 * h) \
            .astype(np.float32)
    ts -= ts.mean()
    got = P.periodicity_search(ts, 16, 4, 64)
    bins = [int(b) for b in np.asarray(got.bins)]
    harm = [int(h) for h in np.asarray(got.harmonics)]
    # the winner needed summing (harmonics > 1), and the fundamental
    # is in the top candidates with a multi-harmonic level of its own
    assert harm[0] > 1, (bins, harm)
    assert 20 in bins[:2], bins
    assert harm[bins.index(20)] > 1, (bins, harm)
    assert float(np.asarray(got.snr)[0]) > 8.0


def test_fold_uniform_series_is_flat():
    ts = np.ones(256, np.float32)
    prof = np.asarray(P.fold(ts, np.int32(7), 16))
    np.testing.assert_allclose(prof, 1.0, rtol=1e-6)


# ------------------------------------------------------------------
# the processor: superset result, parity with the base plan


@pytest.fixture(scope="module")
def raw_segment():
    return make_dispersed_baseband(N, 1405.0, 64.0, 0.0,
                                   pulse_positions=N // 2,
                                   pulse_amp=30.0, nbits=8)


def test_processor_superset_of_single_pulse(raw_segment):
    base = SegmentProcessor(_cfg())
    per = registry.build_processor(_cfg(search_mode="periodicity"))
    assert isinstance(per, PeriodicitySegmentProcessor)
    wf_b, det_b = base.process(raw_segment)
    wf_p, det_p = per.process(raw_segment)
    assert isinstance(det_p, PeriodicityResult)
    # the single-pulse half is BIT-identical (same chain, same trace)
    np.testing.assert_array_equal(np.asarray(wf_b), np.asarray(wf_p))
    np.testing.assert_array_equal(np.asarray(det_b.signal_counts),
                                  np.asarray(det_p.signal_counts))
    np.testing.assert_array_equal(np.asarray(det_b.zero_count),
                                  np.asarray(det_p.zero_count))
    np.testing.assert_array_equal(np.asarray(det_b.time_series),
                                  np.asarray(det_p.time_series))
    # candidate shapes: [S, K] / [S, K, bins]
    k = per.cfg.periodicity_candidates
    s = det_p.candidate_snr.shape[0]
    assert det_p.candidate_bins.shape == (s, k)
    assert det_p.folded_profiles.shape == \
        (s, k, per.cfg.periodicity_fold_bins)
    # the candidates agree with the oracle run on the SAME ts
    ts = np.asarray(det_p.time_series)[0]
    o_bins, _, _, _ = P.periodicity_oracle(
        ts, per.cfg.periodicity_harmonics, k,
        per.cfg.periodicity_fold_bins,
        min_bin=per.cfg.periodicity_min_bin)
    np.testing.assert_array_equal(
        np.asarray(det_p.candidate_bins)[0], o_bins)


def test_plan_identity_distinguishes_the_mode():
    base = SegmentProcessor(_cfg())
    per = PeriodicitySegmentProcessor(_cfg(search_mode="periodicity"))
    assert per.plan_name.endswith("+period")
    assert per.plan_signature() != base.plan_signature()
    assert per.MODE == "periodicity"
    # knob changes re-key the plan (AOT must miss cleanly)
    per2 = PeriodicitySegmentProcessor(
        _cfg(search_mode="periodicity", periodicity_fold_bins=32))
    assert per2.plan_signature() != per.plan_signature()


def test_micro_batch_carries_candidates(raw_segment):
    per = registry.build_processor(
        _cfg(search_mode="periodicity", micro_batch_segments=2))
    batch = np.stack([raw_segment, raw_segment])
    wf, det = per.process_batch(batch)
    assert det.candidate_snr.shape[0] == 2
    np.testing.assert_array_equal(np.asarray(det.candidate_bins)[0],
                                  np.asarray(det.candidate_bins)[1])


def test_periodic_baseband_detected_end_to_end():
    """A pulse train in the BASEBAND surfaces as a high-SNR folding
    candidate at the train's bin, versus a noise-only segment."""
    period = 1024  # baseband samples; 1 waterfall bin = N/T samples
    train = make_dispersed_baseband(
        N, 1405.0, 64.0, 0.0,
        pulse_positions=list(range(period // 2, N - 64, period)),
        pulse_amp=60.0, pulse_width=16, nbits=8, seed=3)
    noise = make_dispersed_baseband(N, 1405.0, 64.0, 0.0,
                                    pulse_positions=[], nbits=8,
                                    seed=4)
    # a strong pulse train is maximally kurtotic: keep the SK zap out
    # of the way (the crash-soak recipe) or the whole waterfall zaps
    # to zero and the time series is empty
    per = registry.build_processor(
        _cfg(search_mode="periodicity",
             mitigate_rfi_average_method_threshold=1000.0,
             mitigate_rfi_spectral_kurtosis_threshold=50.0))
    _, det_t = per.process(train)
    _, det_n = per.process(noise)
    t_len = np.asarray(det_t.time_series).shape[-1]
    fundamental = t_len // (period // (N // t_len))
    bins = [int(b) for b in np.asarray(det_t.candidate_bins)[0]]
    # every train candidate sits ON the comb (multiples of the
    # fundamental: the period really was found)...
    assert all(b % fundamental == 0 for b in bins), (bins,
                                                     fundamental)
    assert bins[0] in (fundamental, 2 * fundamental), bins
    # ...while the noise run's candidates don't line up on any comb
    nbins = [int(b) for b in np.asarray(det_n.candidate_bins)[0]]
    assert any(b % fundamental != 0 for b in nbins), nbins
    # the top candidate's fold concentrates the pulse
    prof = np.asarray(det_t.folded_profiles)[0, 0]
    assert prof.max() > 3 * np.median(np.abs(prof)), prof


def _mk_result(snr, trials=(1, 1)):
    """A host-side PeriodicityResult with zero boxcar hits and the
    given candidate scores — exercises the result type's OWN
    positive_gate hook, the way has_signal consumes it."""
    snr = np.asarray(snr, np.float32)
    k = snr.shape[-1]
    return PeriodicityResult(
        zero_count=np.zeros(1, np.int32),
        time_series=np.zeros((1, 8), np.float32),
        boxcar_lengths=(1,),
        signal_counts=np.zeros((1, 3), np.int32),
        boxcar_series=np.zeros((1, 1, 8), np.float32),
        snr_peaks=np.zeros((1, 3), np.float32),
        candidate_bins=np.zeros((1, k), np.int32),
        candidate_snr=snr,
        candidate_harmonics=np.ones((1, k), np.int32),
        folded_profiles=np.zeros((1, k, 4), np.float32),
        candidate_trials=trials)


def test_has_signal_gates_on_candidate_snr():
    cfg = _cfg(search_mode="periodicity",
               periodicity_snr_threshold=6.0)
    # trials (1, 1): gate = 6 + ln(2) ~ 6.7
    assert has_signal(cfg, _mk_result([[7.0, 1.0]])) is True
    assert has_signal(cfg, _mk_result([[3.0, 1.0]])) is False
    # trials correction: the same raw score over many searched bins
    # is just the noise maximum — the gate moves to ln(trials) +
    # margin and only a genuinely exceptional score fires
    t = (100, 4)  # gate = 6 + ln(400) ~ 12.0
    assert has_signal(cfg, _mk_result([[7.0, 1.0]], t)) is False
    assert has_signal(cfg, _mk_result([[13.0, 1.0]], t)) is True


def test_noise_segments_not_positive_end_to_end(tmp_path):
    """The verify-run regression: a pure-noise file in periodicity
    mode must NOT mark every segment positive (the uncorrected gate
    fired on the noise maximum of ~M*L exponential trials)."""
    import os

    from srtb_tpu.pipeline.runtime import Pipeline

    n = 1 << 17
    path = os.path.join(str(tmp_path), "noise.bin")
    np.random.default_rng(42).integers(
        0, 256, size=2 * n, dtype=np.uint8).tofile(path)
    cfg = _cfg(search_mode="periodicity",
               baseband_input_count=n,
               spectrum_channel_count=1 << 8,
               signal_detect_signal_noise_threshold=8.0,
               input_file_path=path,
               baseband_output_file_prefix=os.path.join(
                   str(tmp_path), "out_"),
               writer_thread_count=0)
    with Pipeline(cfg, sinks=[]) as pipe:
        stats = pipe.run()
    assert stats.segments >= 2
    assert stats.signals == 0, \
        "noise segments read positive: the periodicity gate is not " \
        "trials-corrected"


def test_candidates_persisted_to_disk_and_journal(tmp_path):
    """The mode's science product survives the drain: positive
    segments write <base>.fold.npy ([K, n_bins] profiles) +
    <base>.cand.json (candidate table), and every segment's
    candidates land in the journal span."""
    import json as _json
    import os

    from srtb_tpu.pipeline.runtime import Pipeline

    n = 1 << 13
    path = os.path.join(str(tmp_path), "bb.bin")
    make_dispersed_baseband(n * 2, 1405.0, 64.0, 0.0,
                            pulse_positions=[n // 2, n + n // 2],
                            pulse_amp=40.0, nbits=8).tofile(path)
    journal = os.path.join(str(tmp_path), "j.jsonl")
    cfg = _cfg(search_mode="periodicity",
               baseband_input_count=n,
               signal_detect_signal_noise_threshold=2.0,
               input_file_path=path,
               baseband_output_file_prefix=os.path.join(
                   str(tmp_path), "out_"),
               writer_thread_count=0,
               telemetry_journal_path=journal)
    with Pipeline(cfg) as pipe:
        stats = pipe.run()
    assert stats.signals > 0
    names = sorted(os.listdir(str(tmp_path)))
    folds = [f for f in names if f.endswith(".fold.npy")]
    cands = [f for f in names if f.endswith(".cand.json")]
    assert folds and len(folds) == len(cands)
    prof = np.load(os.path.join(str(tmp_path), folds[0]))
    assert prof.shape == (cfg.periodicity_candidates,
                          cfg.periodicity_fold_bins)
    with open(os.path.join(str(tmp_path), cands[0])) as f:
        meta = _json.load(f)
    assert len(meta["bins"]) == len(meta["snr"]) \
        == len(meta["harmonics"]) == cfg.periodicity_candidates
    with open(journal) as f:
        recs = [_json.loads(ln) for ln in f if ln.strip()]
    spans = [r for r in recs if r.get("type") == "segment_span"]
    assert spans and all("periodicity" in r for r in spans)
    assert spans[0]["periodicity"]["bins"][0]


def test_ladder_demotes_out_of_periodicity_end_to_end(tmp_path):
    """A device OOM on the periodicity plan demotes through the
    search_mode rung: the run completes on the single-pulse plan with
    the demotion accounted, and the single-pulse outputs survive."""
    import os

    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.utils.metrics import metrics

    n = 1 << 13
    path = os.path.join(str(tmp_path), "bb.bin")
    make_dispersed_baseband(n * 3, 1405.0, 64.0, 0.0,
                            pulse_positions=n, nbits=8).tofile(path)
    cfg = _cfg(search_mode="periodicity",
               baseband_input_count=n,
               input_file_path=path,
               baseband_output_file_prefix=os.path.join(
                   str(tmp_path), "out_"),
               writer_thread_count=0,
               inflight_segments=2,
               fault_plan="dispatch:oom@1",
               retry_backoff_base_s=0.001)

    class Cap:
        def __init__(self):
            self.out = []

        def push(self, w, p):
            self.out.append(type(w.detect).__name__)

    metrics.reset()
    cap = Cap()
    with Pipeline(cfg, sinks=[cap]) as pipe:
        stats = pipe.run()
        assert pipe.faults.unfired() == []
        # demoted plan: single-pulse, the +period suffix gone
        assert "+period" not in pipe.processor.plan_name
    assert stats.segments >= 3
    assert metrics.get("plan_demotions") >= 1
    # pre-fault segments carried candidates; post-demotion ones are
    # plain DetectResults — both drain through the same sink
    assert "PeriodicityResult" in cap.out
    assert "DetectResult" in cap.out
    metrics.reset()
