"""FFT layer tests.

Oracle style follows the reference: a golden FFT (numpy, standing in for
FFTW in test-fft_wrappers.cpp:29-67) over size sweeps, including the
four-step decomposition and the half-size-C2C R2C trick
(ref: fft/fft_1d_r2c_post_process.hpp, naive_fft.hpp:219-261).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srtb_tpu.ops import fft as F


@pytest.mark.parametrize("log2n", [5, 8, 12, 16, 20])
def test_rfft_drop_nyquist(log2n):
    n = 1 << log2n
    rng = np.random.default_rng(log2n)
    x = rng.standard_normal(n).astype(np.float32)
    got = np.asarray(F.rfft_drop_nyquist(jnp.asarray(x)))
    expected = np.fft.rfft(x)[:-1]
    assert got.shape == (n // 2,)
    np.testing.assert_allclose(got, expected.astype(np.complex64),
                               rtol=1e-4, atol=1e-2 * np.sqrt(n))


def test_c2c_backward_unnormalized():
    """Backward C2C must be unnormalized (cuFFT convention): ifft(fft(x)) ==
    n * x."""
    n = 1024
    rng = np.random.default_rng(0)
    x = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(
        np.complex64)
    y = np.asarray(F.c2c_backward(F.c2c_forward(jnp.asarray(x))))
    np.testing.assert_allclose(y, n * x, rtol=1e-4, atol=1e-3 * n)


@pytest.mark.parametrize("log2n", [6, 10, 14, 18])
@pytest.mark.parametrize("inverse", [False, True])
def test_four_step_fft(log2n, inverse):
    n = 1 << log2n
    rng = np.random.default_rng(log2n)
    x = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(
        np.complex64)
    got = np.asarray(F.four_step_fft(jnp.asarray(x), inverse=inverse))
    expected = np.fft.ifft(x) * n if inverse else np.fft.fft(x)
    np.testing.assert_allclose(got, expected.astype(np.complex64),
                               rtol=1e-3, atol=2e-2 * np.sqrt(n))


@pytest.mark.parametrize("log2n", [4, 8, 12, 16])
@pytest.mark.parametrize("use_four_step", [False, True])
def test_rfft_via_c2c(log2n, use_four_step):
    n = 1 << log2n
    rng = np.random.default_rng(log2n)
    x = rng.standard_normal(n).astype(np.float32)
    got = np.asarray(F.rfft_via_c2c(jnp.asarray(x),
                                    use_four_step=use_four_step))
    expected = np.fft.rfft(x)
    assert got.shape == (n // 2 + 1,)
    np.testing.assert_allclose(got, expected.astype(np.complex64),
                               rtol=1e-3, atol=2e-2 * np.sqrt(n))


def test_waterfall_layout():
    """Waterfall output must be frequency-major: row i is the unnormalized
    backward C2C of the i-th contiguous sub-band (ref: fft_pipe.hpp:295-343,
    signal_detect_pipe.hpp:305-316 indexing)."""
    channels, watfft_len = 8, 32
    n = channels * watfft_len
    rng = np.random.default_rng(3)
    spec = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(
        np.complex64)
    wf = np.asarray(F.waterfall_c2c(jnp.asarray(spec), channels))
    assert wf.shape == (channels, watfft_len)
    for i in range(channels):
        row = spec[i * watfft_len:(i + 1) * watfft_len]
        expected = np.fft.ifft(row) * watfft_len
        np.testing.assert_allclose(wf[i], expected.astype(np.complex64),
                                   rtol=1e-4, atol=1e-3 * watfft_len)


def test_ifft_refft_waterfall():
    """Alternate path (ref: fft_pipe.hpp:88-278): ifft back to time domain,
    trim the reserved tail, chunked forward FFT; time-major output."""
    n = 1 << 10
    channel_count = 32
    reserved = 64
    rng = np.random.default_rng(9)
    spec = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(
        np.complex64)
    out = np.asarray(F.ifft_refft_waterfall(jnp.asarray(spec), channel_count,
                                            reserved))
    td = np.fft.ifft(spec) * n
    td = td[: n - reserved]
    batch = td.size // channel_count
    expected = np.fft.fft(td[: batch * channel_count]
                          .reshape(batch, channel_count), axis=-1)
    assert out.shape == (batch, channel_count)
    np.testing.assert_allclose(out, expected.astype(np.complex64),
                               rtol=1e-3, atol=0.5)


class TestMxuFFT:
    """DFT-matmul FFT (ops/mxu_fft.py) vs float64 numpy — same oracle
    discipline as the four-step cases.  Runs on CPU via the identical
    einsum graph the TPU executes on its MXU."""

    def test_c2c_forward_and_inverse(self):
        from srtb_tpu.ops.mxu_fft import mxu_fft
        rng = np.random.default_rng(3)
        n = 1 << 16
        x = (rng.standard_normal(n)
             + 1j * rng.standard_normal(n)).astype(np.complex64)
        got = np.asarray(jax.jit(mxu_fft)(jnp.asarray(x)))
        ref = np.fft.fft(x.astype(np.complex128))
        err = np.abs(got - ref) / np.abs(ref).mean()
        assert err.max() < 5e-5
        # unnormalized inverse: ifft(fft(x)) == n * x
        rt = np.asarray(jax.jit(
            lambda v: mxu_fft(mxu_fft(v), inverse=True))(jnp.asarray(x)))
        np.testing.assert_allclose(rt / n, x, atol=2e-4)

    def test_c2c_batched(self):
        from srtb_tpu.ops.mxu_fft import mxu_fft
        rng = np.random.default_rng(4)
        x = (rng.standard_normal((3, 1 << 12))
             + 1j * rng.standard_normal((3, 1 << 12))).astype(np.complex64)
        got = np.asarray(jax.jit(mxu_fft)(jnp.asarray(x)))
        ref = np.fft.fft(x.astype(np.complex128), axis=-1)
        assert (np.abs(got - ref) / np.abs(ref).mean()).max() < 5e-5

    def test_segment_rfft_mxu_strategy(self):
        rng = np.random.default_rng(5)
        n = 1 << 18
        x = rng.standard_normal(n).astype(np.float32)
        got = np.asarray(jax.jit(
            lambda v: jnp.stack([(y := F.segment_rfft(v, "mxu")).real,
                                 y.imag]))(jnp.asarray(x)))
        ref = np.fft.rfft(x.astype(np.float64))[:-1]
        err = np.abs((got[0] + 1j * got[1]) - ref) / np.abs(ref).mean()
        assert err.max() < 5e-5

    def test_radix_validation(self):
        from srtb_tpu.ops.mxu_fft import mxu_fft
        with pytest.raises(ValueError, match="power-of-two"):
            mxu_fft(jnp.ones(96, jnp.complex64))
        with pytest.raises(ValueError, match="radix"):
            mxu_fft(jnp.ones(128, jnp.complex64), radix=96)


def test_factored_twiddle_matches_float64_large_n():
    """The factored outer-product _twiddle must keep the exact-residue
    precision of the per-element form at large n (the round-1 bug class:
    f32 phase error at n >= 2^24 costs whole turns)."""
    from srtb_tpu.ops.fft import _twiddle

    n1, n2 = 1 << 11, 1 << 13  # n = 2^24, n2 a multiple of 256
    got = np.asarray(_twiddle(n1, n2, inverse=False))
    # sample rows so the float64 oracle stays tiny (4 rows, not 2^24 pts)
    idx = np.array([0, 1, n1 // 3, n1 - 1])
    j1 = idx.astype(np.float64)[:, None]
    j2 = np.arange(n2, dtype=np.float64)[None, :]
    want = np.exp(-2j * np.pi * (j1 * j2 % (n1 * n2)) / (n1 * n2))
    err = np.abs(got[idx] - want)
    assert err.max() < 5e-6  # ~f32 eps-level phase error, no turns lost


def test_iota_phase_matches_float64_large_m():
    from srtb_tpu.ops.fft import _iota_phase

    m, n = 1 << 22, 1 << 23
    got = np.asarray(_iota_phase(m, n, -1.0))
    k = np.arange(m, dtype=np.float64)
    want = np.exp(-2j * np.pi * k / n)
    assert np.abs(got - want).max() < 5e-6
