"""AOT executable persistence (utils/aot_cache.py, VERDICT r4 #6).

A restarted observation must not pay the XLA compile again when the
persistent compile cache is bypassed: SegmentProcessor.enable_aot
persists the compiled plan executables and a second process-equivalent
build loads them.  CPU backends are opt-in (SRTB_AOT_ALLOW_CPU=1) —
save+load on one host is safe; the default-off policy mirrors
utils/compile_cache.py's host-swap SIGILL rationale.
"""

import glob
import os

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.pipeline.segment import SegmentProcessor


def _cfg(tmp_path, n=1 << 14, **kw):
    return Config(
        baseband_input_count=n,
        baseband_input_bits=2,
        baseband_format_type="simple",
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        dm=30.0,
        spectrum_channel_count=1 << 6,
        signal_detect_max_boxcar_length=16,
        mitigate_rfi_average_method_threshold=1e9,
        mitigate_rfi_spectral_kurtosis_threshold=1e9,
        baseband_reserve_sample=False,
        aot_plan_path=str(tmp_path / "aot"),
        **kw,
    )


def _raw(cfg, seed=3):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=cfg.segment_bytes(1), dtype=np.uint8)


@pytest.mark.parametrize("staged", [False, True],
                         ids=["fused", "staged"])
def test_aot_roundtrip(tmp_path, monkeypatch, staged):
    monkeypatch.setenv("SRTB_AOT_ALLOW_CPU", "1")
    cfg = _cfg(tmp_path)
    raw = _raw(cfg)

    p1 = SegmentProcessor(cfg, staged=staged)
    wf1 = np.asarray(p1.process(raw)[0])
    blobs = glob.glob(str(tmp_path / "aot" / "*.aot"))
    assert len(blobs) == (3 if staged else 1), blobs
    mtimes = {b: os.path.getmtime(b) for b in blobs}

    # "restart": a fresh processor over the same config must LOAD (no
    # blob rewritten) and produce the identical executables' results
    p2 = SegmentProcessor(cfg, staged=staged)
    from jax.stages import Compiled
    progs = ([p2._jit_stage_a, p2._jit_stage_b, p2._jit_stage_c]
             if staged else [p2._jit_process])
    assert all(isinstance(p, Compiled) for p in progs)
    wf2 = np.asarray(p2.process(raw)[0])
    assert {b: os.path.getmtime(b) for b in blobs} == mtimes, \
        "a warm start must not re-save (i.e. must not have recompiled)"
    np.testing.assert_array_equal(wf1, wf2)


def test_aot_signature_miss_recompiles(tmp_path, monkeypatch):
    """A changed plan-shaping knob must miss the cache, not load a
    stale executable for the wrong program."""
    monkeypatch.setenv("SRTB_AOT_ALLOW_CPU", "1")
    cfg = _cfg(tmp_path)
    SegmentProcessor(cfg).process(_raw(cfg))
    n_blobs = len(glob.glob(str(tmp_path / "aot" / "*.aot")))
    cfg2 = cfg.replace(spectrum_channel_count=1 << 5)
    p2 = SegmentProcessor(cfg2)
    p2.process(_raw(cfg2))
    assert len(glob.glob(str(tmp_path / "aot" / "*.aot"))) == 2 * n_blobs


def test_plan_signature_keys_on_trace_shape_only(tmp_path, monkeypatch):
    """The AOT cache key must ignore deployment-local knobs (paths,
    socket buffers) — an operator relocating outputs or tuning IO
    between runs must still hit the cache — while any trace-shaping
    field must miss."""
    monkeypatch.setenv("SRTB_AOT_ALLOW_CPU", "1")
    cfg = _cfg(tmp_path)
    sig = SegmentProcessor(cfg).plan_signature()
    # deployment-local changes: same signature
    same = cfg.replace(baseband_output_file_prefix="/elsewhere/out_",
                       udp_receiver_rcvbuf_bytes=1 << 20,
                       segment_deadline_s=42.0)
    assert SegmentProcessor(same).plan_signature() == sig
    # run-local SRTB_ knobs (bench dirs, watcher logs): same signature
    monkeypatch.setenv("SRTB_BENCH_AOT_DIR", "/tmp/other")
    monkeypatch.setenv("SRTB_WATCH_LOG", "/tmp/w.log")
    assert SegmentProcessor(same).plan_signature() == sig
    # trace-shaping changes: different signature
    assert SegmentProcessor(
        cfg.replace(spectrum_channel_count=1 << 5)).plan_signature() != sig
    assert SegmentProcessor(
        cfg.replace(fft_strategy="four_step")).plan_signature() != sig
    monkeypatch.setenv("SRTB_STAGED_ROWS_IMPL", "pallas")
    assert SegmentProcessor(cfg).plan_signature() != sig


def test_aot_cpu_default_off(tmp_path, monkeypatch):
    """Without the opt-in, CPU backends keep the plain jit wrappers and
    write nothing (the host-swap SIGILL policy)."""
    monkeypatch.delenv("SRTB_AOT_ALLOW_CPU", raising=False)
    import jax
    if jax.default_backend() != "cpu":
        pytest.skip("policy under test is CPU-only")
    cfg = _cfg(tmp_path)
    p = SegmentProcessor(cfg)
    from jax.stages import Compiled
    assert not isinstance(p._jit_process, Compiled)
    assert not glob.glob(str(tmp_path / "aot" / "*.aot"))
