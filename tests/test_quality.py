"""Science observatory tests (ISSUE 16): the data-quality epilogue and
the pulse-injection canary.

Unit layer: packed-vector parity of the device epilogue against the
float64 oracle (direct and through every plan family — monolithic,
fused, staged, front-fused), the EWMA drift detector on a synthetic
bandpass ramp, canary delta determinism and quarantine-by-construction
(reserved spans zeroed), and strict Prometheus exposition for the new
metric families.

E2E layer: canary recovery bit-identical across checkpoint resume;
quarantine proven end to end (canary segments absent from science
outputs, flagged in journal + manifest, ``baseband_write_all`` output
bit-identical to a canary-off run); the sensitivity gate's teeth (a
band-zapped run fails the check, degrades detection health and
escalates an incident bundle carrying the quality timeline)."""

import hashlib
import json
import os

import numpy as np
import pytest
from oracle_utils import oracle_unpack

from srtb_tpu.config import Config
from srtb_tpu.ops import rfi
from srtb_tpu.ops.dedisperse import D, spectrum_frequencies
from srtb_tpu.pipeline.segment import SegmentProcessor
from srtb_tpu.quality import (CanaryController, EWMADrift,
                              QualityMonitor, quality_stats_oracle,
                              unpack_stats)
from srtb_tpu.quality import stats as QS
from srtb_tpu.utils import slo
from srtb_tpu.utils.metrics import metrics


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics.reset()
    slo.reset()
    yield
    metrics.reset()
    slo.reset()


# ------------------------------------------------------- oracle parity


def _proc_cfg(**extra) -> Config:
    return Config(**{**dict(
        baseband_input_count=1 << 14, baseband_input_bits=8,
        baseband_freq_low=1405.0, baseband_bandwidth=64.0,
        baseband_sample_rate=128e6, dm=30.0,
        spectrum_channel_count=1 << 5,
        signal_detect_signal_noise_threshold=5.0,
        signal_detect_max_boxcar_length=8,
        mitigate_rfi_average_method_threshold=10.0,
        mitigate_rfi_spectral_kurtosis_threshold=3.0,
        baseband_reserve_sample=False, quality_stats=True,
        quality_coarse_bins=16), **extra})


def _oracle_spec_wf(x: np.ndarray, cfg: Config):
    """The float64 chain of oracle_utils.oracle_stream_chain, returning
    the two intermediates the quality epilogue reads: the zapped/
    normalized/chirped spectrum and the SK-zapped waterfall."""
    n = x.size
    n_spec = n // 2
    spec = np.fft.rfft(x)[:-1]
    power = spec.real ** 2 + spec.imag ** 2
    zap1 = power > (cfg.mitigate_rfi_average_method_threshold
                    * power.mean())
    coeff = rfi.normalization_coefficient(n_spec,
                                          cfg.spectrum_channel_count)
    spec = np.where(zap1, 0.0, spec * coeff)
    f_min, f_c, df = spectrum_frequencies(cfg, n_spec)
    f = f_min + df * np.arange(n_spec, dtype=np.float64)
    k = D * 1e6 * cfg.dm / f * ((f - f_c) / f_c) ** 2
    spec = spec * np.exp(-2j * np.pi * np.modf(k)[0])
    ch = min(cfg.spectrum_channel_count, n_spec)
    wlen = n_spec // ch
    wf = np.fft.ifft(spec.reshape(ch, wlen), axis=-1) * wlen
    lo, hi = rfi.sk_decision_thresholds(
        wlen, cfg.mitigate_rfi_spectral_kurtosis_threshold)
    p = wf.real ** 2 + wf.imag ** 2
    s2, s4 = p.sum(axis=-1), (p * p).sum(axis=-1)
    sk = wlen * s4 / (s2 * s2)
    wf = np.where(((sk > hi) | (sk < lo))[:, None], 0.0, wf)
    return spec, wf


def _assert_quality_parity(proc: SegmentProcessor, cfg: Config,
                           raw: np.ndarray, tag: str):
    _, res = proc.process(raw)
    assert res.quality is not None
    q_dev = np.asarray(res.quality)
    spec_o, wf_o = _oracle_spec_wf(oracle_unpack(raw, 8), cfg)
    q_or = quality_stats_oracle(spec_o[None], wf_o[None],
                                cfg.quality_coarse_bins,
                                cfg.quality_dead_threshold,
                                cfg.quality_hot_threshold,
                                subsample=cfg.quality_subsample)
    assert q_dev.shape == q_or.shape == (
        1, QS.vector_length(cfg.quality_coarse_bins))
    scale = np.maximum(np.abs(q_or), 1e-9)
    np.testing.assert_allclose(q_dev, q_or, rtol=1e-4,
                               atol=1e-4 * scale.max(),
                               err_msg=f"plan {tag}")


@pytest.mark.parametrize("plan", ["monolithic", "fused", "staged"])
def test_epilogue_oracle_parity(plan):
    """result.quality vs the float64 oracle, per plan family."""
    cfg = _proc_cfg()
    if plan == "monolithic":
        cfg = cfg.replace(fft_strategy="monolithic", fused_tail="off")
    raw = np.random.default_rng(7).integers(
        0, 256, size=cfg.segment_bytes(1), dtype=np.uint8)
    proc = SegmentProcessor(cfg, staged=(plan == "staged"))
    _assert_quality_parity(proc, cfg, raw, plan)


def test_epilogue_oracle_parity_ffuse(monkeypatch):
    """The front-fused staged megakernel computes the same quality
    vector (the epilogue rides its folded spectrum tail)."""
    monkeypatch.setenv("SRTB_STAGED_ROWS_IMPL", "pallas2")
    cfg = _proc_cfg(baseband_input_count=1 << 16,
                    spectrum_channel_count=8, front_fuse="on")
    raw = np.random.default_rng(11).integers(
        0, 256, size=cfg.segment_bytes(1), dtype=np.uint8)
    proc = SegmentProcessor(cfg, staged=True)
    assert proc.front_fuse
    _assert_quality_parity(proc, cfg, raw, "ffuse")


def test_quality_off_is_none():
    """quality_stats off: the epilogue is an exact no-op and existing
    consumers see the None pytree subtree."""
    cfg = _proc_cfg(quality_stats=False)
    raw = np.random.default_rng(7).integers(
        0, 256, size=cfg.segment_bytes(1), dtype=np.uint8)
    _, res = SegmentProcessor(cfg).process(raw)
    assert res.quality is None


def test_unpack_stats_roundtrip():
    """The packed layout is self-describing: unpack_stats recovers the
    coarse-bin count from the vector length."""
    rng = np.random.default_rng(3)
    spec = (rng.normal(size=(2, 256))
            + 1j * rng.normal(size=(2, 256)))
    spec[0, :32] = 0.0  # an eighth of stream 0 zapped
    wf = (rng.normal(size=(2, 16, 16))
          + 1j * rng.normal(size=(2, 16, 16)))
    q = quality_stats_oracle(spec, wf, 8, 0.1, 10.0)
    u = unpack_stats(q)
    assert u["occupancy"].shape == u["bandpass"].shape == (2, 8)
    assert u["zap_frac"][0] == pytest.approx(32 / 256)
    assert u["zap_frac"][1] == pytest.approx(0.0)
    # occupancy localizes the zap to the first bin of stream 0
    assert u["occupancy"][0, 0] == pytest.approx(1.0)
    assert u["occupancy"][0, 1:].max() == pytest.approx(0.0)


# ------------------------------------------------------ drift detector


def test_ewma_drift_triggers_on_ramp():
    """Steady bandpass: no alert.  A bandpass ramp setting in after
    warmup: the alert marks the transition onset (a slow creep within
    the noise is absorbed by design — the EWM variance tracks it)."""
    rng = np.random.default_rng(5)
    steady = EWMADrift(alpha=0.05, threshold=4.0, warmup=8)
    for _ in range(200):
        _, alert = steady.observe(100.0 + rng.normal(0, 1.0))
        assert not alert
    ramp = EWMADrift(alpha=0.05, threshold=4.0, warmup=8)
    alerts = []
    for i in range(200):
        x = 100.0 + rng.normal(0, 1.0) + (max(0, i - 100) * 5.0)
        _, alert = ramp.observe(x)
        alerts.append(alert)
    assert not any(alerts[:101])
    assert any(alerts[101:])


def test_quality_monitor_gauges_and_drift_alert():
    """QualityMonitor.observe exports the gauges (flat + labeled) and
    a ramped bandpass bumps quality_drift_alerts."""
    mon = QualityMonitor(drift_alpha=0.05, drift_threshold=4.0,
                         stream="beamQ")
    b = 4
    rng = np.random.default_rng(9)

    def vec(bp_mean):
        v = np.zeros(QS.N_SCALARS + 2 * b, dtype=np.float32)
        v[QS.IDX_ZAP_FRAC] = 0.25
        v[QS.IDX_BANDPASS_MEAN] = bp_mean
        v[QS.IDX_SK_MEAN] = 1.0
        return v

    for i in range(120):
        bp = 50.0 + rng.normal(0, 0.5) + (max(0, i - 60) * 5.0)
        out = mon.observe(vec(bp), segment=i)
    assert metrics.get("quality_zap_fraction") == pytest.approx(0.25)
    assert metrics.get("quality_zap_fraction",
                       labels={"stream": "beamQ"}) == pytest.approx(0.25)
    assert metrics.get("quality_drift_alerts") >= 1
    assert out["drift_score"] > 0
    tl = mon.timeline()
    assert tl and tl[-1]["segment"] == 119
    assert len(tl) <= QS.TIMELINE_SPANS


def test_quality_monitor_from_config_none_hook():
    assert QualityMonitor.from_config(Config(quality_stats=False)) \
        is None
    assert QualityMonitor.from_config(Config(quality_stats=True)) \
        is not None


# ---------------------------------------------- prometheus exposition


def test_prometheus_quality_canary_families_strict():
    """Satellite 1: the science-observatory families render with real
    (non-generic) HELP text, exactly one HELP + one TYPE each, HELP
    first, samples contiguous — a strict expfmt parser accepts the
    whole page."""
    mon = QualityMonitor(drift_alpha=0.05, drift_threshold=4.0,
                         stream="beam0")
    mon.observe(np.zeros(QS.N_SCALARS + 8, dtype=np.float32))
    cfg = Config(baseband_input_count=1 << 12,
                 canary_every_segments=4, canary_expected_snr=10.0,
                 stream_name="beam0")
    can = CanaryController.from_config(cfg)
    can.check(3, np.array([8.0]))
    text = metrics.prometheus()
    lines = text.strip().split("\n")
    seen_help, seen_type, current, order = {}, {}, None, []
    for ln in lines:
        if ln.startswith("# HELP "):
            name = ln.split()[2]
            seen_help[name] = seen_help.get(name, 0) + 1
            assert len(ln.split(" ", 3)) == 4 and ln.split(" ", 3)[3]
        elif ln.startswith("# TYPE "):
            name = ln.split()[2]
            seen_type[name] = seen_type.get(name, 0) + 1
            assert seen_help.get(name) == seen_type[name]
            current = name
            order.append(name)
        else:
            sample = ln.split("{")[0].split(" ")[0]
            assert sample == current or sample.startswith(
                current + "_"), (sample, current)
            float(ln.rpartition(" ")[2])
    assert seen_help == seen_type
    assert all(v == 1 for v in seen_type.values())
    assert len(order) == len(set(order))  # no re-opened family
    generic = "srtb_tpu runtime metric"
    for fam in ("quality_zap_fraction", "quality_sk_max",
                "quality_drift_score", "canary_checked",
                "canary_sensitivity_ratio", "detection_health_state"):
        help_ln = [ln for ln in lines
                   if ln.startswith(f"# HELP srtb_{fam} ")]
        assert len(help_ln) == 1, fam
        assert generic not in help_ln[0], fam
        # the labeled twin rides the same family block
        assert any(ln.startswith(f"srtb_{fam}{{") for ln in lines), fam


# ------------------------------------------------------- canary units


def _canary_cfg(**extra) -> Config:
    kw = dict(baseband_input_count=1 << 12,
              baseband_input_bits=8, baseband_freq_low=1405.0,
              baseband_bandwidth=64.0, baseband_sample_rate=128e6,
              canary_every_segments=3)
    kw.update(extra)
    return Config(**kw)


def test_canary_delta_deterministic_and_quarantined():
    """Two controllers build the identical int16 delta (bit-identical
    across resume by construction), zeroed over the head/tail reserved
    spans so the pulse can never leak through overlap or ring carry."""
    cfg = _canary_cfg()
    a = CanaryController(cfg, n_samples=1 << 12, reserved_samples=256)
    b = CanaryController(cfg, n_samples=1 << 12, reserved_samples=256)
    da, db = a._build_delta(), b._build_delta()
    np.testing.assert_array_equal(da, db)
    assert da.dtype == np.int16 and len(da) == 1 << 12
    assert np.abs(da[256:-256]).max() > 0  # pulse present...
    assert not da[:256].any() and not da[-256:].any()  # ...quarantined
    # schedule: never the cold first segment, every `every`-th after
    assert [a.is_canary(i) for i in range(7)] == [
        False, False, True, False, False, True, False]


def test_canary_prepare_pristine_and_size_gate():
    cfg = _canary_cfg()
    can = CanaryController.from_config(cfg)
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, size=1 << 12, dtype=np.uint8)
    before = data.copy()
    out, mark = can.prepare(2, data)
    np.testing.assert_array_equal(data, before)  # input untouched
    assert mark is not None and out is not data
    assert out.dtype == np.uint8 and (out != data).any()
    # non-canary index: passthrough, no copy
    same, no_mark = can.prepare(3, data)
    assert same is data and no_mark is None
    # a partial tail segment skips injection loudly
    tail = data[: 1 << 10]
    short, m2 = can.prepare(5, tail)
    assert m2 is None and short is tail


def test_canary_from_config_gates():
    assert CanaryController.from_config(Config()) is None
    assert CanaryController.from_config(
        _canary_cfg(baseband_input_bits=2)) is None
    assert CanaryController.from_config(
        _canary_cfg(baseband_format_type="naocpsr_snap1",
                    baseband_input_bits=-8)) is None
    assert CanaryController.from_config(_canary_cfg()) is not None


def test_canary_check_autocalibrate_and_slo():
    """First check calibrates; a later weak recovery fails the ratio
    gate, flips detection health and feeds the SLO sensitivity
    objective."""
    slo.configure(Config(slo_sensitivity_budget=0.1,
                         stream_name="beamC"))
    can = CanaryController.from_config(
        _canary_cfg(stream_name="beamC"))
    v1 = can.check(2, np.array([12.0]))
    assert v1["calibrated"] and v1["ok"] and v1["ratio"] == 1.0
    assert metrics.get("detection_health_state") == 0
    v2 = can.check(5, np.array([3.0]))
    assert not v2["ok"] and v2["ratio"] == pytest.approx(0.25)
    assert metrics.get("detection_health_state") == 1
    assert metrics.get("detection_health_state",
                       labels={"stream": "beamC"}) == 1
    assert metrics.get("canary_failed") == 1
    assert "sensitivity" in slo.tracker.objectives


# --------------------------------------------------------- e2e helpers


def _noise_file(tmp_path, n, segments, seed=7):
    rng = np.random.default_rng(seed)
    path = str(tmp_path / f"noise{seed}.bin")
    (rng.normal(128, 8, n * segments)
     ).clip(0, 255).astype(np.uint8).tofile(path)
    return path


def _e2e_cfg(tmp_path, tag, n=1 << 14, segments=6, **extra):
    return Config(
        baseband_input_count=n, baseband_input_bits=8,
        baseband_freq_low=1405.0, baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        input_file_path=_noise_file(tmp_path, n, segments),
        baseband_output_file_prefix=str(tmp_path / f"{tag}_"),
        spectrum_channel_count=1 << 6,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        dm=0.0, baseband_reserve_sample=False,
        writer_thread_count=0, retry_backoff_base_s=0.001,
        inflight_segments=3, **extra)


def _journal_spans(path):
    out = []
    for line in open(path):
        line = line.strip()
        if line.startswith("{"):
            rec = json.loads(line)
            if rec.get("type") == "segment_span":
                out.append(rec)
    return out


# ---------------------------------------------------------- e2e canary


def test_canary_recovery_bit_identical_across_resume(tmp_path):
    """An interrupted + resumed run injects the same pulses on the
    same absolute segments and recovers bit-identical S/N (the
    resume-continuous ``_canary_base`` schedule + the deterministic
    delta)."""
    from srtb_tpu.pipeline.runtime import Pipeline

    def verdicts(journal):
        return [(r["segment"], r["canary"].get("snr"),
                 r["canary"].get("ok"))
                for r in _journal_spans(journal) if "canary" in r]

    j_full = str(tmp_path / "full.jsonl")
    cfg = _e2e_cfg(tmp_path, "full", canary_every_segments=2,
                   telemetry_journal_path=j_full)
    with Pipeline(cfg, sinks=[]) as pipe:
        assert pipe.run().segments == 6
    full = verdicts(j_full)
    assert len(full) == 3 and all(v[1] is not None for v in full)

    j_res = str(tmp_path / "resumed.jsonl")
    cfg2 = _e2e_cfg(tmp_path, "res", canary_every_segments=2,
                    telemetry_journal_path=j_res,
                    checkpoint_path=str(tmp_path / "ck.json"))
    with Pipeline(cfg2, sinks=[]) as pipe:
        pipe.run(max_segments=3)  # "crash" after an odd count
    with Pipeline(cfg2, sinks=[]) as pipe:
        pipe.run()
    assert verdicts(j_res) == full  # same segments, bit-equal S/N


def test_canary_quarantine_e2e(tmp_path):
    """The injected pulse IS loud enough to cross the detection
    threshold, yet no science artifact appears: the candidate sink
    never sees a canary segment, the journal + manifest carry the
    flags, and detection health stays OK."""
    from srtb_tpu.io.manifest import scan_manifest
    from srtb_tpu.pipeline.runtime import Pipeline

    journal = str(tmp_path / "q.jsonl")
    mfile = str(tmp_path / "manifest.jsonl")
    cfg = _e2e_cfg(tmp_path, "quar", canary_every_segments=2,
                   signal_detect_signal_noise_threshold=6.0,
                   telemetry_journal_path=journal,
                   run_manifest_path=mfile)
    with Pipeline(cfg) as pipe:  # default WriteSignalSink
        stats = pipe.run()
    assert stats.segments == 6
    assert metrics.get("canary_checked") == 3
    assert metrics.get("canary_failed") == 0
    # recovered S/N crossed the science threshold -> without the
    # quarantine these segments would have dumped candidates
    assert metrics.get("canary_last_snr") > 6.0
    assert stats.signals == 0
    produced = [f for f in os.listdir(tmp_path)
                if f.startswith("quar_") and not f.endswith(".bin")]
    assert produced == []
    spans = _journal_spans(journal)
    flagged = {r["segment"] for r in spans if "canary" in r}
    assert flagged == {1, 3, 5}
    assert all(r["canary"]["ok"] for r in spans if "canary" in r)
    # run manifest carries the canary records (tolerated by scan)
    recs = [json.loads(ln) for ln in open(mfile)
            if ln.strip().startswith("{")]
    canaries = [r for r in recs if r.get("t") == "canary"]
    assert {r["abs"] for r in canaries} == {1, 3, 5}
    assert all(r["ok"] for r in canaries)
    scan_manifest(mfile)  # unknown-record tolerance


def test_write_all_bit_identical_with_canary(tmp_path):
    """Tentpole acceptance: the contiguous baseband output of a
    canary-on run is byte-identical to a canary-off run — the sinks
    only ever see the pristine bytes (canary_exempt appender)."""
    from srtb_tpu.pipeline.runtime import Pipeline

    digests = {}
    for tag, every in [("coff", 0), ("con", 2)]:
        cfg = _e2e_cfg(tmp_path, tag, segments=4,
                       baseband_write_all=True,
                       canary_every_segments=every)
        with Pipeline(cfg) as pipe:
            assert pipe.run().segments == 4
        outs = sorted(f for f in os.listdir(tmp_path)
                      if f.startswith(f"{tag}_"))
        assert len(outs) == 1
        digests[tag] = hashlib.sha256(
            open(os.path.join(tmp_path, outs[0]), "rb").read()
        ).hexdigest()
    assert digests["con"] == digests["coff"]


def test_canary_gate_teeth_incident_and_health(tmp_path):
    """A run whose RFI config zaps the band out from under the pulse
    fails the sensitivity check: detection health degrades, /healthz
    grows the detection section, and an incident bundle lands with
    the canary verdict + quality timeline as extra.json."""
    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.utils import telemetry

    clean = _e2e_cfg(tmp_path, "clean", segments=4,
                     canary_every_segments=2)
    with Pipeline(clean, sinks=[]) as pipe:
        pipe.run()
    expected = metrics.get("canary_last_snr")
    assert expected > 5.0
    metrics.reset()

    inc_dir = str(tmp_path / "incidents")
    degraded = _e2e_cfg(
        tmp_path, "deg", segments=4,
        canary_every_segments=2, quality_stats=True,
        canary_expected_snr=expected,
        mitigate_rfi_freq_list="1405-1466",
        incident_dir=inc_dir, incident_min_interval_s=0.0)
    with Pipeline(degraded, sinks=[]) as pipe:
        pipe.run()
    assert metrics.get("canary_failed") >= 1
    assert metrics.get("detection_health_state") == 1
    assert metrics.get("canary_sensitivity_ratio") < 0.5
    health = telemetry.health()
    assert health["detection"]["state"] == "degraded"
    assert health["detection"]["sensitivity_ratio"] < 0.5
    bundles = [d for d in os.listdir(inc_dir)
               if "canary_sensitivity" in d]
    assert bundles
    extra = json.load(open(os.path.join(
        inc_dir, bundles[0], "extra.json")))
    assert extra["canary"]["ok"] is False
    assert extra["canary"]["ratio"] < 0.5
    assert isinstance(extra["quality_timeline"], list)
    assert extra["quality_timeline"]  # quality rode along


def test_quality_journal_and_report_tools(tmp_path, capsys):
    """quality_stats journals the v9 extra and both report tools
    render it; empty journals exit 0 with a note (satellite 2)."""
    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.tools import quality_report as QR
    from srtb_tpu.tools import telemetry_report as TR

    journal = str(tmp_path / "j.jsonl")
    cfg = _e2e_cfg(tmp_path, "rep", segments=4, quality_stats=True,
                   canary_every_segments=2,
                   telemetry_journal_path=journal)
    with Pipeline(cfg, sinks=[]) as pipe:
        pipe.run()
    spans = _journal_spans(journal)
    assert all(r["v"] == 11 and "quality" in r for r in spans)
    q = spans[0]["quality"]
    assert set(q) >= {"zap_frac", "bandpass_mean", "sk_max",
                      "drift_score", "occupancy", "bandpass"}
    assert len(q["occupancy"]) == Config().quality_coarse_bins

    assert QR.main([journal, "--format", "json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["records"] == 4
    assert rep["canary"][""]["checked"] == 2
    assert rep["quality"][""]["records"] == 4
    assert QR.main([journal]) == 0
    md = capsys.readouterr().out
    assert "Data quality" in md and "Canary" in md
    # the general report still summarizes v9 spans
    assert TR.main([journal, "--format", "json"]) == 0
    assert json.loads(capsys.readouterr().out)["records"] == 4

    # satellite 2: empty / missing journals exit 0 with a note
    empty = str(tmp_path / "empty.jsonl")
    open(empty, "w").close()
    for tool in (TR, QR):
        assert tool.main([empty, "--format", "json"]) == 0
        assert json.loads(capsys.readouterr().out)["records"] == 0
        assert tool.main([str(tmp_path / "missing.jsonl")]) == 0
        capsys.readouterr()


def test_quality_ladder_rung_first_and_family_registered():
    """The registry integration: the quality rung sheds the epilogue
    before any science, is a no-op when the epilogue is off, and the
    audited plan family exists (ladder=False: never demoted INTO)."""
    from srtb_tpu.pipeline import registry as R
    from srtb_tpu.resilience.demote import ladder_rungs

    assert R.ladder_order()[0] == "quality"
    fam = R.family("four_step_ftail_quality")
    assert fam is not None and not fam.ladder
    assert fam.cfg["quality_stats"] is True

    on = _proc_cfg()
    rungs = ladder_rungs(on, base_staged=False)
    assert rungs[0].step == "quality"
    assert rungs[0].cfg.quality_stats is False
    off = _proc_cfg(quality_stats=False)
    assert [r.step for r in ladder_rungs(off, base_staged=False)
            if r.step == "quality"] == []
