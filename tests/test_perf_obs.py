"""Performance observatory (ISSUE 14): always-on device-time
accounting + live roofline gauges, the perf ledger/trajectory, the
noise-aware regression gate's statistics, and the on-demand
jax.profiler capture hook."""

import glob
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.utils import perf_ledger as PL
from srtb_tpu.utils import perf_stats as PS
from srtb_tpu.utils.metrics import metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------- stats (satellite)


def test_clear_regression_flagged():
    """A 10% slowdown over a ~4%-noise distribution must be flagged:
    the Mann-Whitney p collapses, the bootstrap CI excludes zero, and
    the effect clears the computed floor."""
    rng = np.random.default_rng(7)
    a = rng.normal(1.00, 0.04, 30)
    b = rng.normal(1.10, 0.044, 30)
    v = PS.compare(a, b)
    assert v["regression"] and not v["improvement"], v
    assert v["p"] < 0.01 and v["ci_low"] > 0.0
    assert 0.05 < v["effect"] < 0.16


def test_small_shift_inside_noise_not_flagged():
    """A 1% shift inside a 4%-noise distribution is indistinguishable
    from sampling noise: the gate must NOT cry regression."""
    rng = np.random.default_rng(11)
    a = rng.normal(1.00, 0.04, 20)
    b = rng.normal(1.01, 0.04, 20)
    v = PS.compare(a, b)
    assert not v["regression"], v
    assert v["effect"] < v["threshold"] or v["p"] >= v["alpha"], v


def test_noise_floor_formalizes_the_4pct_eyeball():
    """With ~4%-sigma samples at the historical rep count (9), the
    computed floor lands in the same territory as PERF.md's hand
    ±4% — the constant was an okay eyeball, now derived."""
    rng = np.random.default_rng(3)
    a = rng.normal(1.0, 0.04, 9)
    b = rng.normal(1.0, 0.04, 9)
    floor = PS.noise_floor(a, b)
    assert 0.01 < floor < 0.10, floor
    # floor shrinks with more reps (sqrt-n), grows with scatter
    big = rng.normal(1.0, 0.04, 100)
    assert PS.noise_floor(big, big) < floor


def test_mann_whitney_identical_and_ties():
    u, p = PS.mann_whitney_u([1.0] * 10, [1.0] * 10)
    assert p == 1.0  # all ties: zero variance path, no false verdict
    _, p2 = PS.mann_whitney_u([1, 2, 3, 4, 5], [1, 2, 3, 4, 5])
    assert p2 > 0.5
    # an unambiguous separation
    _, p3 = PS.mann_whitney_u(list(range(10)), list(range(20, 30)))
    assert p3 < 0.001


def test_bootstrap_ci_deterministic_and_brackets_effect():
    rng = np.random.default_rng(5)
    a = rng.normal(1.0, 0.03, 25)
    b = rng.normal(1.2, 0.03, 25)
    ci1 = PS.bootstrap_effect_ci(a, b, seed=42)
    ci2 = PS.bootstrap_effect_ci(a, b, seed=42)
    assert ci1 == ci2  # seeded: verdicts reproduce
    assert ci1[0] < 0.2 < ci1[1] or abs(0.2 - ci1[1]) < 0.05


def test_improvement_symmetric():
    rng = np.random.default_rng(9)
    a = rng.normal(1.10, 0.03, 25)
    b = rng.normal(1.00, 0.03, 25)
    v = PS.compare(a, b)
    assert v["improvement"] and not v["regression"]


# ------------------------------------------------------- perf ledger


def test_ledger_roundtrip_and_record_fields(tmp_path):
    path = str(tmp_path / "led.jsonl")
    led = PL.PerfLedger(path)
    rec = PL.make_record("bench", 123.4, "Msamples/s", plan="p",
                         plan_signature="sig-blob",
                         shape={"log2n": 20}, platform="cpu",
                         samples_s=[0.1, 0.2],
                         extra={"k": 1})
    assert led.append(rec)
    out = PL.load(path)
    assert len(out) == 1
    r = out[0]
    assert r["value"] == 123.4 and r["source"] == "bench"
    assert r["plan_signature_sha"] == PL.signature_sha("sig-blob")
    assert len(r["plan_signature_sha"]) == 16
    assert r["host_fp"] == PL.host_fingerprint()
    assert r["samples_s"] == [0.1, 0.2]
    # foreign/torn lines tolerated
    with open(path, "a") as f:
        f.write('{"type": "other"}\nnot json\n{"type": "perf_rec')
    assert len(PL.load(path)) == 1


def test_legacy_bench_import_idempotent(tmp_path):
    """Satellite: the legacy BENCH_r0*.json artifacts (the REAL ones
    checked into this repo) import into the ledger, failed rounds
    included as value-0 outage records, and a re-import is a no-op."""
    from srtb_tpu.tools import perf_ledger as CLI
    path = str(tmp_path / "led.jsonl")
    pat = os.path.join(REPO, "BENCH_r0*.json")
    assert glob.glob(pat), "legacy BENCH artifacts missing from repo"
    assert CLI.main([path, "--import", pat]) == 0
    recs = PL.load(path)
    assert len(recs) == len(glob.glob(pat))
    measured = [r for r in recs if r["value"] > 0]
    failed = [r for r in recs if r["value"] == 0]
    assert measured and failed  # the repo history holds both kinds
    assert all(r["source"] == "import" for r in recs)
    # provenance honesty: the importer's host/git must not be stamped
    assert all(r["host_fp"] == "" and r["git_sha"] == "" for r in recs)
    assert any(r["extra"].get("roofline_frac") for r in measured)
    # idempotent second import
    assert CLI.main([path, "--import", pat]) == 0
    assert len(PL.load(path)) == len(recs)


def test_perf_report_renders_trajectory(tmp_path, capsys):
    from srtb_tpu.tools import perf_ledger as CLI
    from srtb_tpu.tools import perf_report as PR
    path = str(tmp_path / "led.jsonl")
    CLI.main([path, "--import", os.path.join(REPO, "BENCH_r0*.json")])
    capsys.readouterr()
    assert PR.main([path, "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["records"] >= 4 and doc["groups"]
    # at least one measured group with a best value
    assert any(g["best"] > 0 for g in doc["groups"].values())
    md_rc = PR.main([path])
    md = capsys.readouterr().out
    assert md_rc == 0 and "# Perf trajectory" in md and "| when |" in md
    # empty ledger renders a clear note and exits 0 (dashboards
    # scrape before the first record lands)
    empty = str(tmp_path / "empty.jsonl")
    open(empty, "w").close()
    assert PR.main([empty]) == 0
    out = capsys.readouterr().out
    assert "no perf records" in out


# ---------------------------------------------------------- the gate


def test_gate_cross_host_calibration():
    """A baseline from another host is rescaled by the calibration
    ratio and gated at the raised smoke-alarm floor."""
    from srtb_tpu.tools import perf_gate as PG
    base = {"samples_s": [1.0] * 16, "calib_s": 0.5, "host_fp": "aaaa"}
    cur = {"samples_s": [2.05] * 8 + [2.1] * 8, "calib_s": 1.0,
           "host_fp": "bbbb"}
    v = PG.gate(base, cur)
    # calib says this host is 2x slower: baseline scales to ~2.0 and
    # the ~3% residual sits far below the cross-host floor
    assert v["cross_host"] and v["calibration_scale"] == 2.0
    assert v["min_effect"] == PG.CROSS_HOST_MIN_EFFECT
    assert not v["regression"], v
    # a genuine 2x regression on top of calibration still fails
    cur2 = {"samples_s": [4.2] * 16, "calib_s": 1.0, "host_fp": "bbbb"}
    assert PG.gate(base, cur2)["regression"]
    # cross-host WITHOUT calibration is incomparable at any floor:
    # flagged, never a (guaranteed-false) verdict
    base_nocal = {"samples_s": [1.0] * 16, "host_fp": "aaaa"}
    v3 = PG.gate(base_nocal, cur2)
    assert v3["uncalibrated_cross_host"]
    assert not v3["regression"] and not v3["improvement"]


def test_stall_plan_uses_fault_machinery():
    from srtb_tpu.resilience.faults import FaultInjector
    from srtb_tpu.tools import perf_gate as PG
    plan = PG.stall_plan(segments=3, warmup=2, stall_s=0.05)
    inj = FaultInjector.from_plan(plan)
    assert inj is not None
    by_index = inj._by_site["dispatch"]
    assert sorted(by_index) == [2, 3, 4]
    assert all(s.action == "stall" and s.arg == 0.05
               for s in by_index.values())


def test_gate_selftest_proves_detection():
    """Acceptance: perf_gate --selftest — the injected dispatch stall
    fails the gate, the clean rerun passes inside the computed
    floor.  Run tiny so it fits the tier-1 budget."""
    from srtb_tpu.tools import perf_gate as PG
    rc = PG.main(["--selftest", "--segments", "10", "--warmup", "3",
                  "--log2n", "12", "--channels", "16"])
    assert rc == 0


def test_gate_baseline_roundtrip(tmp_path, capsys):
    """--write-baseline then --baseline on the same host: same code,
    same machine -> pass; and the capture lands in the ledger."""
    from srtb_tpu.tools import perf_gate as PG
    base = str(tmp_path / "base.json")
    led = str(tmp_path / "led.jsonl")
    args = ["--segments", "8", "--warmup", "2", "--log2n", "12",
            "--channels", "16"]
    assert PG.main(["--write-baseline", base] + args) == 0
    capsys.readouterr()
    rc = PG.main(["--baseline", base, "--ledger", led] + args)
    v = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    if rc != 0:
        # clean/clean false-alarms with probability ~alpha/2 on a
        # loaded host — one independent recapture, the same bound the
        # gate's own selftest uses (a real regression fails both)
        rc = PG.main(["--baseline", base, "--ledger", led] + args)
        v = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert not v["cross_host"] and v["calibration_scale"] == 1.0
    recs = PL.load(led)
    assert len(recs) >= 1 and recs[0]["source"] == "gate"
    assert len(recs[0]["samples_s"]) == 8


# ------------------------- device-time accounting + roofline gauges


def _obs_cfg(tmp_path, n, **kw):
    from srtb_tpu.io.synth import make_dispersed_baseband
    bb = str(tmp_path / "bb.bin")
    segs = kw.pop("segments", 3)
    make_dispersed_baseband(n * segs, 1405.0, 64.0, 0.0,
                            pulse_positions=n // 2,
                            nbits=8).tofile(bb)
    return Config(
        baseband_input_count=n, baseband_input_bits=8,
        baseband_freq_low=1405.0, baseband_bandwidth=64.0,
        baseband_sample_rate=128e6, dm=0.0, input_file_path=bb,
        baseband_output_file_prefix=str(tmp_path / "out_"),
        spectrum_channel_count=kw.pop("spectrum_channel_count", 32),
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        baseband_reserve_sample=False, writer_thread_count=0, **kw)


def test_device_accounting_v8_spans_and_gauges(tmp_path):
    """Every drained segment of the async engine journals device_ms +
    roofline_frac + achieved_msamps (v8) plus the cumulative
    compile/cache books, and the live gauges + device_seconds
    histogram land on /metrics — with per-stream labeled twins for a
    named lane."""
    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.tools import telemetry_report as TR
    n = 1 << 13
    journal = str(tmp_path / "j.jsonl")
    cfg = _obs_cfg(tmp_path, n, segments=4, inflight_segments=2,
                   telemetry_journal_path=journal,
                   stream_name="beam7")
    metrics.reset()
    with Pipeline(cfg, sinks=[]) as pipe:
        stats = pipe.run()
    assert stats.segments == 4
    recs = TR.load(journal)
    assert len(recs) == 4
    for r in recs:
        assert r["v"] == 11
        assert r["device_ms"] > 0
        assert r["roofline_frac"] > 0 and r["achieved_msamps"] > 0
        assert r["aot_cache_hits"] == 0 and r["aot_cache_misses"] == 0
    # first dispatch = the run's one (lazy-jit) compile event, and the
    # named span carries the stream's OWN labeled books
    assert recs[-1]["plan_compiles"] == 1
    assert recs[-1]["compile_ms"] > 0
    assert metrics.get("plan_compiles",
                       labels={"stream": "beam7"}) == 1
    # device_ms is concurrent, never inside the host stage sum
    assert "device" not in recs[0]["stages_ms"]
    # live gauges + labeled twins
    for g in ("roofline_frac", "achieved_msamps", "achieved_gbps"):
        assert metrics.get(g) > 0
        assert metrics.get(g, labels={"stream": "beam7"}) > 0
    prom = metrics.prometheus()
    assert "# TYPE srtb_device_seconds histogram" in prom
    assert 'srtb_roofline_frac{stream="beam7"}' in prom
    assert 'srtb_plan_compiles{stream="beam7"}' in prom
    # roofline sanity: the gauge equals the plan-floor model over the
    # journaled device wall (lower-bound contract)
    proc = pipe.processor
    model_bytes = proc._segment_bytes + 8.0 * proc.n_spectrum \
        * proc.hbm_passes
    last = recs[-1]
    expect = model_bytes / (last["device_ms"] / 1e3) / 1e9 \
        / cfg.hbm_peak_gbps
    assert abs(last["roofline_frac"] - expect) < 0.05 * expect + 1e-4
    # report surfaces the device section
    rep = TR.report(journal)
    assert rep["device"]["records"] == 4
    assert rep["device"]["plan_compiles"] == 1
    md = TR._md(rep)
    assert "## Device time (performance observatory)" in md


def test_serial_device_time_is_exact_fetch_wall(tmp_path):
    """inflight_segments=1: device_ms is the dispatch->blocking-fetch
    wall — it must be >= the fetch stage and bounded by the segment's
    host wall + fetch (no queue-wait inflation in serial mode)."""
    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.tools import telemetry_report as TR
    n = 1 << 13
    journal = str(tmp_path / "j.jsonl")
    cfg = _obs_cfg(tmp_path, n, segments=3, inflight_segments=1,
                   telemetry_journal_path=journal)
    metrics.reset()
    with Pipeline(cfg, sinks=[]) as pipe:
        pipe.run()
    for r in TR.load(journal):
        assert r["device_ms"] >= r["stages_ms"]["fetch"] * 0.99
        # serial: nothing else runs between dispatch and fetch
        total = sum(r["stages_ms"].values())
        assert r["device_ms"] <= total + 50.0


def test_threaded_pipeline_omits_unmeasured_device_time(tmp_path):
    """ThreadedPipeline does not measure the dispatch->ready wall: its
    spans must OMIT device_ms (never journal a fake 0), while the
    compile/cache books still ride along."""
    from srtb_tpu.pipeline.runtime import ThreadedPipeline
    from srtb_tpu.tools import telemetry_report as TR
    n = 1 << 13
    journal = str(tmp_path / "j.jsonl")
    cfg = _obs_cfg(tmp_path, n, segments=3,
                   telemetry_journal_path=journal)
    metrics.reset()
    with ThreadedPipeline(cfg, sinks=[]) as pipe:
        stats = pipe.run()
    recs = TR.load(journal)
    assert len(recs) == stats.segments >= 2
    for r in recs:
        assert r["v"] == 11
        assert "device_ms" not in r and "roofline_frac" not in r
        assert "compile_ms" in r and "plan_compiles" in r


def test_aot_cache_hit_miss_counters(tmp_path, monkeypatch):
    """The AOT protocol's cache economics are counters now: a cold
    build records misses + exact compile seconds, a warm restart
    records hits and no new compile."""
    from srtb_tpu.pipeline.segment import SegmentProcessor
    monkeypatch.setenv("SRTB_AOT_ALLOW_CPU", "1")
    n = 1 << 12
    cfg = Config(
        baseband_input_count=n, baseband_input_bits=8,
        baseband_freq_low=1405.0, baseband_bandwidth=64.0,
        baseband_sample_rate=128e6, dm=0.0,
        spectrum_channel_count=16,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        baseband_reserve_sample=False, fft_strategy="four_step",
        aot_plan_path=str(tmp_path / "aot"))
    metrics.reset()
    p1 = SegmentProcessor(cfg)
    assert p1.aot_active
    assert metrics.get("aot_cache_misses") >= 1
    assert metrics.get("aot_cache_hits") == 0
    assert metrics.get("compile_seconds") > 0
    compiles0 = metrics.get("plan_compiles")
    # warm restart: loads, compiles nothing
    p2 = SegmentProcessor(cfg)
    assert p2.aot_active
    assert metrics.get("aot_cache_hits") >= 1
    assert metrics.get("plan_compiles") == compiles0
    # an AOT-active first dispatch is NOT a lazy-jit compile event
    rng = np.random.default_rng(0)
    raw = rng.integers(0, 256, size=cfg.segment_bytes(1),
                       dtype=np.uint8)
    p2.process(raw)
    assert metrics.get("plan_compiles") == compiles0


def test_profile_capture_hook(tmp_path):
    """Config.profile_capture_segments records a real jax.profiler
    trace of the first N segments with a capture.json sidecar whose
    trace_ids join the journal spans."""
    from srtb_tpu.pipeline.runtime import Pipeline
    from srtb_tpu.tools import telemetry_report as TR
    n = 1 << 12
    cap = str(tmp_path / "prof")
    journal = str(tmp_path / "j.jsonl")
    cfg = _obs_cfg(tmp_path, n, segments=3, inflight_segments=1,
                   spectrum_channel_count=16,
                   telemetry_journal_path=journal,
                   profile_capture_segments=2,
                   profile_capture_dir=cap)
    metrics.reset()
    with Pipeline(cfg, sinks=[]) as pipe:
        stats = pipe.run()
    assert stats.segments == 3
    side = os.path.join(cap, "capture.json")
    if not os.path.exists(side):
        pytest.skip("jax.profiler unavailable on this backend")
    doc = json.load(open(side))
    assert doc["segments"] == 2
    assert doc["first_segment"] == 0 and doc["last_segment"] == 1
    # the sidecar's trace_ids are the journal's — the join key between
    # the device timeline and the causal-event/journal timeline
    recs = TR.load(journal)
    tids = [r.get("trace_id") for r in recs[:2]]
    assert [doc["first_trace_id"], doc["last_trace_id"]] == tids
    assert metrics.get("profile_captures") == 1
    # the capture wrote actual profiler artifacts next to the sidecar
    files = [f for _, _, fs in os.walk(cap) for f in fs
             if f != "capture.json"]
    assert files, "no profiler trace files written"


def test_steady_state_ledger_never_aborts_the_run(tmp_path):
    """An unwritable ledger path reduces to a warning: the run it was
    supposed to describe still completes and returns stats."""
    from srtb_tpu.pipeline.runtime import Pipeline
    n = 1 << 12
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("file, not a directory")
    cfg = _obs_cfg(tmp_path, n, segments=2, inflight_segments=1,
                   spectrum_channel_count=16,
                   perf_ledger_path=str(blocker / "led.jsonl"))
    metrics.reset()
    with Pipeline(cfg, sinks=[]) as pipe:
        stats = pipe.run()
    assert stats.segments == 2  # the record failed, the run did not


def test_steady_state_ledger_record(tmp_path):
    from srtb_tpu.pipeline.runtime import Pipeline
    n = 1 << 12
    led = str(tmp_path / "led.jsonl")
    cfg = _obs_cfg(tmp_path, n, segments=3, inflight_segments=2,
                   spectrum_channel_count=16, perf_ledger_path=led)
    metrics.reset()
    with Pipeline(cfg, sinks=[]) as pipe:
        stats = pipe.run()
    recs = PL.load(led)
    assert len(recs) == 1
    r = recs[0]
    assert r["source"] == "steady" and r["unit"] == "Msamples/s"
    assert r["extra"]["segments"] == stats.segments == 3
    assert r["shape"]["log2n"] == 12
    assert r["plan"] and r["plan_signature_sha"]


# --------------------------------------------------- bench satellite


def test_bench_uniform_compile_and_cache_fields(tmp_path):
    """Satellite: bench.py emits compile_ms (one semantics across AOT
    and lazy-jit protocols), the cache hit/miss/compile deltas, and
    per-rep samples — and --ledger lands the measurement in the perf
    ledger."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    env["SRTB_BENCH_LOG2N"] = "13"
    env["SRTB_BENCH_REPS"] = "4"
    led = str(tmp_path / "led.jsonl")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--overlap", "off", "--ledger", led],
        env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads([ln for ln in out.stdout.strip().splitlines()
                      if ln.startswith("{")][-1])
    assert rec["compile_ms"] > 0
    # lazy-jit path on CPU: one first-dispatch compile, no AOT traffic
    assert rec["plan_compiles"] >= 1
    assert rec["aot_cache_hits"] == 0 and rec["aot_cache_misses"] == 0
    assert len(rec["rep_seconds"]) == 4
    assert all(s > 0 for s in rec["rep_seconds"])
    lrecs = PL.load(led)
    assert len(lrecs) == 1 and lrecs[0]["source"] == "bench"
    assert lrecs[0]["samples_s"] == rec["rep_seconds"]
    assert lrecs[0]["extra"]["overlap"] == "off"
