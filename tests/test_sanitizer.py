"""Runtime sanitizer tests (analysis/sanitizer.py + Config.sanitize).

Covers the acceptance criteria: a sanitized pipeline run passes on
clean synth input (serial and overlapped), while seeded violations —
a NaN, an implicit device->host transfer, a use-after-donate, a
wrong-thread touch, a leaked thread — are each trapped with an
actionable message.  Plus the zero-cost-off contract: with
``sanitize=False`` the pipeline holds no sanitizer and numpy stays
unpatched.
"""

import threading
import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srtb_tpu.analysis import sanitizer as S
from srtb_tpu.analysis.sanitizer import Sanitizer, SanitizerError
from srtb_tpu.config import Config
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.pipeline.runtime import Pipeline
from srtb_tpu.pipeline.segment import SegmentProcessor
from srtb_tpu.pipeline.work import SegmentWork

# ------------------------------------------------------------ fixtures


@pytest.fixture(scope="module")
def synth_file(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("sanitize")
    n = 1 << 14
    data = make_dispersed_baseband(n * 3, 1405.0, 64.0, 0.0,
                                   pulse_positions=n, nbits=8)
    path = str(tmp / "bb.bin")
    data.tofile(path)
    return path, n


def _cfg(path, n, tmp_path, tag, **extra):
    return Config(
        baseband_input_count=n,
        baseband_input_bits=8,
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        input_file_path=path,
        baseband_output_file_prefix=str(tmp_path / f"{tag}_"),
        spectrum_channel_count=1 << 7,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        baseband_reserve_sample=False,
        writer_thread_count=0,
        sanitize=True,
        **extra)


class _StubDetect(NamedTuple):
    signal_counts: np.ndarray
    zero_count: np.ndarray
    time_series: np.ndarray


def _stub_det(counts=0.0, nan=False):
    ts = np.zeros(8, np.float32)
    if nan:
        ts[3] = np.nan
    return _StubDetect(
        signal_counts=np.full((1, 4), counts, np.float32),
        zero_count=np.asarray(0), time_series=ts)


class _StubProcessor:
    def __init__(self, nan=False):
        self.nan = nan

    def process(self, raw):
        return None, _stub_det(nan=self.nan)


class _Source:
    def __init__(self, n=3, seg_bytes=64):
        self._it = iter(
            SegmentWork(data=np.zeros(seg_bytes, np.uint8),
                        timestamp=i + 1) for i in range(n))

    def __iter__(self):
        return self._it


# ------------------------------------------------- acceptance: clean


@pytest.mark.parametrize("window", [1, 3])
def test_sanitized_pipeline_passes_on_clean_input(
        synth_file, tmp_path, window):
    path, n = synth_file
    cfg = _cfg(path, n, tmp_path, f"ok{window}",
               inflight_segments=window)
    with Pipeline(cfg, sinks=[]) as pipe:
        stats = pipe.run()
    assert stats.segments == 3
    # tripwire uninstalled: numpy is pristine again
    assert not hasattr(np.asarray, "_srtb_sanitize_orig")
    assert not hasattr(np.array, "_srtb_sanitize_orig")


def test_sanitize_off_is_zero_cost(synth_file, tmp_path):
    path, n = synth_file
    cfg = _cfg(path, n, tmp_path, "off").replace(sanitize=False)
    pipe = Pipeline(cfg, sinks=[])
    assert pipe.sanitizer is None
    with pipe:
        assert pipe.run().segments == 3
    assert not hasattr(np.asarray, "_srtb_sanitize_orig")


# ------------------------------------------------------ NaN tripwire


def test_seeded_nan_is_trapped(tmp_path):
    cfg = Config(baseband_input_count=64, sanitize=True,
                 baseband_output_file_prefix=str(tmp_path / "nan_"),
                 inflight_segments=1)
    pipe = Pipeline(cfg, source=_Source(), sinks=[],
                    processor=_StubProcessor(nan=True))
    with pytest.raises(SanitizerError, match="non-finite.*detect"):
        pipe.run()


def test_seeded_nan_trapped_through_sink_pipe(tmp_path):
    # overlapped mode: the tripwire fires on the sink thread and must
    # still fail the run loudly
    cfg = Config(baseband_input_count=64, sanitize=True,
                 baseband_output_file_prefix=str(tmp_path / "nan2_"),
                 inflight_segments=2)
    pipe = Pipeline(cfg, source=_Source(), sinks=[],
                    processor=_StubProcessor(nan=True))
    with pytest.raises(SanitizerError, match="non-finite"):
        pipe.run()


def test_check_finite_device_and_contract_units():
    with pytest.raises(SanitizerError, match="stage_x"):
        S.check_finite("stage_x", jnp.asarray([1.0, jnp.inf]))
    S.check_finite("ok", jnp.arange(4.0))            # clean
    S.check_finite("ints", np.arange(4))             # non-float leaf
    wf = jnp.zeros((2, 1, 4, 4), jnp.float32)
    S.check_contract("wf", wf, ndim=4, lead=2, dtype=np.float32)
    with pytest.raises(SanitizerError, match="leading axis 2"):
        S.check_contract("wf", wf[0], lead=2)
    with pytest.raises(SanitizerError, match="expected ndim 4"):
        S.check_contract("wf", wf[0], ndim=4)
    with pytest.raises(SanitizerError, match="dtype drift"):
        S.check_contract("wf", wf.astype(jnp.int32), dtype=np.float32)


# ----------------------------------------- implicit-transfer tripwire


def test_implicit_transfer_trapped_direct():
    san = Sanitizer()
    x = jnp.arange(8.0)
    with san.run_scope():
        with pytest.raises(SanitizerError, match="implicit.*transfer"):
            np.asarray(x)
        with pytest.raises(SanitizerError, match="implicit"):
            np.array(x)
        # the sanctioned explicit spelling stays allowed
        assert jax.device_get(x)[3] == 3.0
        # host data is unaffected
        assert np.asarray([1, 2]).sum() == 3
    # restored after the scope
    assert np.asarray(x)[1] == 1.0


def test_implicit_transfer_in_sink_trapped(synth_file, tmp_path):
    path, n = synth_file

    class BadSink:
        wants_waterfall = True

        def push(self, work, positive):
            np.asarray(work.waterfall)  # implicit D2H on a device wf

    cfg = _cfg(path, n, tmp_path, "bad", inflight_segments=2)
    pipe = Pipeline(cfg, sinks=[BadSink()])
    with pytest.raises(SanitizerError, match="device_get"):
        pipe.run()
    assert not hasattr(np.asarray, "_srtb_sanitize_orig")


def test_nested_scopes_refcount():
    a, b = Sanitizer(), Sanitizer()
    x = jnp.arange(4.0)
    with a.run_scope():
        with b.run_scope():
            with pytest.raises(SanitizerError):
                np.asarray(x)
        # still armed: outer scope alive
        with pytest.raises(SanitizerError):
            np.asarray(x)
    assert np.asarray(x)[0] == 0.0


# ------------------------------------------------- use-after-donate


def _small_cfg(tmp_path, **extra):
    return Config(baseband_input_count=1 << 12,
                  baseband_input_bits=8,
                  baseband_freq_low=1405.0, baseband_bandwidth=64.0,
                  baseband_sample_rate=128e6,
                  spectrum_channel_count=1 << 6,
                  baseband_reserve_sample=False,
                  baseband_output_file_prefix=str(tmp_path / "d_"),
                  **extra)


def test_use_after_donate_trapped(tmp_path):
    cfg = _small_cfg(tmp_path, sanitize=True)
    proc = SegmentProcessor(cfg, donate_input=True)
    raw = proc.stage_input(
        np.random.default_rng(0).integers(
            0, 255, cfg.baseband_input_count, dtype=np.uint8))
    wf, det = proc.run_device(raw)
    assert np.isfinite(jax.device_get(det.time_series)).all()
    # the donated input is now expired: any read raises loudly, on
    # CPU too (where donation itself is a no-op)
    with pytest.raises(RuntimeError, match="deleted"):
        jax.device_get(raw)


def test_no_expiry_without_sanitize(tmp_path):
    cfg = _small_cfg(tmp_path, sanitize=False)
    proc = SegmentProcessor(cfg, donate_input=True)
    raw = proc.stage_input(
        np.zeros(cfg.baseband_input_count, dtype=np.uint8))
    proc.run_device(raw)
    jax.device_get(raw)  # CPU donation is a no-op; nothing expired


def test_staged_boundary_checks_run(tmp_path):
    cfg = _small_cfg(tmp_path, sanitize=True)
    proc = SegmentProcessor(cfg, staged=True, donate_input=True)
    raw = proc.stage_input(
        np.random.default_rng(1).integers(
            0, 255, cfg.baseband_input_count, dtype=np.uint8))
    wf, det = proc.run_device(raw)   # contracts + finite per boundary
    assert wf.shape[0] == 2
    with pytest.raises(RuntimeError, match="deleted"):
        jax.device_get(raw)


# ------------------------------------------------- thread ownership


def test_thread_ownership_guard():
    san = Sanitizer()
    san.assert_owner("inflight_window")      # main claims
    san.assert_owner("inflight_window")      # same thread: fine
    err = []

    def intruder():
        try:
            san.assert_owner("inflight_window")
        except SanitizerError as e:
            err.append(e)

    t = threading.Thread(target=intruder)
    t.start()
    t.join()
    assert err and "thread-ownership violation" in str(err[0])
    san.release_owners()
    # after release the state is claimable again
    san.assert_owner("inflight_window")


# ------------------------------------------------ leaked-thread check


def test_leaked_thread_trapped():
    san = Sanitizer()
    stop = threading.Event()
    leaker = threading.Thread(target=stop.wait, name="leaky_sink",
                              daemon=True)
    try:
        with pytest.raises(SanitizerError, match="leaky_sink"):
            with san.run_scope():
                leaker.start()
    finally:
        stop.set()
        leaker.join()


def test_joined_thread_is_clean():
    san = Sanitizer()
    with san.run_scope():
        t = threading.Thread(target=lambda: time.sleep(0.01))
        t.start()
        t.join()


def test_leaked_threads_helper_allows_pools():
    from srtb_tpu.utils import termination
    snap = termination.thread_snapshot()
    done = threading.Event()
    t = threading.Thread(target=done.wait,
                         name="ThreadPoolExecutor-9_0", daemon=True)
    t.start()
    try:
        assert termination.leaked_threads(snap, grace_s=0.0) == []
    finally:
        done.set()
        t.join()
