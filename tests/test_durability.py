"""Durable exactly-once outputs (ISSUE 10): run-manifest WAL, crash
recovery, checkpoint hardening, fsck, and the crash windows.

The in-process tests simulate crashes with injected FATAL faults (the
run dies mid-window, Python-level state is abandoned exactly where a
SIGKILL would abandon it for the synchronous-writer paths) and with
hand-built mid-crash filesystem states; the real-SIGKILL subprocess
soak (tools/crash_soak.py) is the slow acceptance gate."""

import json
import os
import zlib

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.io import manifest as M
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.pipeline.checkpoint import StreamCheckpoint
from srtb_tpu.pipeline.runtime import Pipeline
from srtb_tpu.pipeline.segment import SegmentProcessor
from srtb_tpu.tools import fsck as F
from srtb_tpu.tools.crash_soak import (make_resumable_source,
                                       snapshot_outputs)
from srtb_tpu.utils.metrics import metrics

KEY = (0, 0, "0:WriteSignalSink")


# ----------------------------------------------------------------
# manifest WAL unit tests
# ----------------------------------------------------------------

def _write_artifact(path, payload=b"artifact-bytes" * 8):
    with open(path, "wb") as f:
        f.write(payload)
    return payload


def test_manifest_roundtrip(tmp_path):
    mpath = str(tmp_path / "m.jsonl")
    m = M.RunManifest.open(mpath)
    p = str(tmp_path / "out_1.bin")
    payload = _write_artifact(p)
    m.intent(KEY, p)
    m.commit(KEY, p, len(payload), zlib.crc32(payload))
    m.sink_done(KEY)
    m.checkpoint(1, 4096)
    assert m.is_done(KEY) and not m.is_done((0, 1, "x"))
    m.close()

    scan = M.scan_manifest(mpath)
    assert not scan.torn and scan.bad_line is None
    assert scan.checkpoint_floor() == 1
    grp = scan.groups[KEY]
    assert M.group_complete(grp)
    art = grp.artifacts[p]
    assert art.committed and art.length == len(payload) \
        and art.crc32 == zlib.crc32(payload)
    # reopen: the done-set survives the process boundary
    m2 = M.RunManifest.open(mpath)
    assert m2.is_done(KEY)
    m2.close()


def test_manifest_torn_tail_truncated(tmp_path):
    mpath = str(tmp_path / "m.jsonl")
    m = M.RunManifest.open(mpath)
    m.sink_done(KEY)
    m.close()
    good = os.path.getsize(mpath)
    with open(mpath, "ab") as f:
        f.write(b'{"t":"done","half-written')  # torn mid-append
    rep = M.recover(mpath, apply=True)
    assert rep.truncated_bytes > 0
    assert os.path.getsize(mpath) == good
    assert KEY in rep.done


def test_manifest_forged_crc_invalidates_tail(tmp_path):
    """Everything after the first bad record is untrusted: later
    groups drop out of the done-set (their segments re-drain on
    resume) while the valid prefix keeps its guarantees.  Artifacts
    the forgotten records had published become untracked files —
    detected by fsck's torn-WAL error, deliberately not deleted
    (recovery only removes files the valid prefix names)."""
    mpath = str(tmp_path / "m.jsonl")
    m = M.RunManifest.open(mpath)
    key2 = (0, 1, "0:WriteSignalSink")
    p1 = str(tmp_path / "out_1.bin")
    p2 = str(tmp_path / "out_2.bin")
    pay1 = _write_artifact(p1)
    m.intent(KEY, p1)
    m.commit(KEY, p1, len(pay1), zlib.crc32(pay1))
    m.sink_done(KEY)
    pay2 = _write_artifact(p2)
    m.intent(key2, p2)
    m.commit(key2, p2, len(pay2), zlib.crc32(pay2))
    m.sink_done(key2)
    m.close()
    # forge a byte inside segment 1's intent record
    with open(mpath, "rb+") as f:
        data = f.read()
        i = data.rindex(b'"intent"')
        f.seek(i)
        f.write(b'"iNtent"')
    rep = M.recover(mpath, apply=True)
    assert KEY in rep.done and key2 not in rep.done
    assert os.path.exists(p1)
    assert rep.truncated_bytes > 0
    # p2 is untracked (its records fell past the corruption): left on
    # disk for the operator, the torn WAL is the loud signal
    assert os.path.exists(p2)


def test_recover_rolls_back_uncommitted_intent(tmp_path):
    metrics.reset()
    mpath = str(tmp_path / "m.jsonl")
    m = M.RunManifest.open(mpath)
    p = str(tmp_path / "out_1.bin")
    m.intent(KEY, p)
    # crash here: temp on disk, and a second flavor where the rename
    # happened but the commit record never landed
    _write_artifact(p + M.TMP_SUFFIX)
    p2 = str(tmp_path / "out_2.npy")
    m.intent(KEY, p2)
    _write_artifact(p2)
    m.close()
    rep = M.recover(mpath, apply=True)
    assert rep.rolled_back_intents == 2
    assert not os.path.exists(p + M.TMP_SUFFIX)
    assert not os.path.exists(p2)
    assert KEY not in rep.done
    # the metric lands when the pipeline reopens the manifest
    metrics.reset()
    M.RunManifest.open(mpath).close()
    assert metrics.get("rolled_back_intents") == 0  # already recovered


def test_recover_truncates_torn_append(tmp_path):
    mpath = str(tmp_path / "m.jsonl")
    m = M.RunManifest.open(mpath)
    p = str(tmp_path / "stream0.bin")
    chunk = b"chunk-one-bytes!"
    m.intent(KEY, p, mode="append", offset=0)
    with open(p, "wb") as f:
        f.write(chunk)
    m.commit(KEY, p, len(chunk), zlib.crc32(chunk), offset=0)
    m.sink_done(KEY)
    key2 = (0, 1, "0:WriteAllSink")
    m.intent(key2, p, mode="append", offset=len(chunk))
    with open(p, "ab") as f:
        f.write(b"torn-append-that-never-committed")
    m.close()
    rep = M.recover(mpath, apply=True)
    assert KEY in rep.done and key2 not in rep.done
    assert os.path.getsize(p) == len(chunk)
    with open(p, "rb") as f:
        assert f.read() == chunk


def test_recover_done_set_and_recovered_counter(tmp_path):
    """A committed group BEYOND the checkpoint is the rescued window:
    counted as recovered and skipped on replay."""
    metrics.reset()
    mpath = str(tmp_path / "m.jsonl")
    m = M.RunManifest.open(mpath)
    p = str(tmp_path / "out_5.bin")
    pay = _write_artifact(p)
    m.checkpoint(5, 1 << 16)
    key5 = (0, 5, "0:WriteSignalSink")
    m.intent(key5, p)
    m.commit(key5, p, len(pay), zlib.crc32(pay))
    m.sink_done(key5)
    m.close()
    m2 = M.RunManifest.open(mpath)
    assert m2.is_done(key5)
    assert metrics.get("recovered_segments") == 1
    m2.close()
    metrics.reset()


def test_recover_honors_checkpoint_floor_hint(tmp_path):
    """A WAL that lost its ckpt records (mid-file corruption) must not
    roll back artifacts in segments the checkpoint FILE says are done
    — the resume would never regenerate them.  The checkpoint floor
    hint raises the effective floor so the gap is flagged, not
    deleted."""
    mpath = str(tmp_path / "m.jsonl")
    m = M.RunManifest.open(mpath)
    p = str(tmp_path / "out_7.bin")
    pay = _write_artifact(p)
    key7 = (0, 7, "0:WriteSignalSink")
    m.intent(key7, p)
    m.close()
    # the commit/done/ckpt records for segment 7 were lost to
    # corruption; the checkpoint file still says 10 segments done
    rep = M.recover(mpath, apply=True, checkpoint_floor_hint=10)
    assert os.path.exists(p)          # NOT rolled back
    assert rep.rolled_back_intents == 0
    assert rep.missing                # flagged as possible loss
    # without the hint the gap segment would be rolled back
    rep2 = M.recover(mpath, apply=True)
    assert not os.path.exists(p)


def test_recover_append_gap_not_truncated(tmp_path):
    """Append flavor of the checkpoint-floor guard: bytes beyond the
    SURVIVING committed prefix that belong to segments the checkpoint
    sealed (but a corrupted WAL forgot) are flagged, never cut."""
    mpath = str(tmp_path / "m.jsonl")
    m = M.RunManifest.open(mpath)
    p = str(tmp_path / "stream0.bin")
    chunk = b"committed-chunk!"
    m.intent(KEY, p, mode="append", offset=0)
    with open(p, "wb") as f:
        f.write(chunk)
    m.commit(KEY, p, len(chunk), zlib.crc32(chunk), offset=0)
    m.sink_done(KEY)
    # segment 1's append happened and WAS sealed, but its commit/done/
    # ckpt records were lost to WAL corruption: only the intent remains
    key1 = (0, 1, "0:WriteAllSink")
    m.intent(key1, p, mode="append", offset=len(chunk))
    with open(p, "ab") as f:
        f.write(b"sealed-but-forgotten")
    m.close()
    size = os.path.getsize(p)
    rep = M.recover(mpath, apply=True, checkpoint_floor_hint=2)
    assert os.path.getsize(p) == size          # untouched
    assert any("forgotten" in s for s in rep.missing)
    # without the hint the overhang is an ordinary torn append
    rep2 = M.recover(mpath, apply=True)
    assert os.path.getsize(p) == len(chunk)


def test_native_drain_commits_verified_per_job(tmp_path, monkeypatch):
    """An errored native drain batch must not drop commits for jobs
    that verifiably landed (temp+rename is all-or-nothing, so a final
    file at the submitted size proves success)."""
    from srtb_tpu.io.native_writer import AsyncWriterPool
    if not __import__("srtb_tpu.io.native_writer",
                      fromlist=["native_available"]).native_available():
        pytest.skip("native writer not built")
    pool = AsyncWriterPool(2, prefer_native=True)
    good = str(tmp_path / "good.bin")
    bad = str(tmp_path / "no_dir" / "bad.bin")
    fired = []
    pool.submit(good, b"payload!", on_done=lambda: fired.append("good"))
    pool.submit(bad, b"payload!", on_done=lambda: fired.append("bad"))
    pool.drain()
    assert fired == ["good"]
    with pytest.raises(RuntimeError):
        pool.raise_new_errors("test")
    # a later clean batch commits normally
    good2 = str(tmp_path / "good2.bin")
    pool.submit(good2, b"x", on_done=lambda: fired.append("good2"))
    pool.drain()
    assert fired == ["good", "good2"]
    pool.close()


def test_recover_flags_missing_below_checkpoint(tmp_path):
    """A committed artifact that vanished UNDER the checkpoint is
    unrecoverable loss: flagged, never silently repaired."""
    mpath = str(tmp_path / "m.jsonl")
    m = M.RunManifest.open(mpath)
    p = str(tmp_path / "out_1.bin")
    pay = _write_artifact(p)
    m.intent(KEY, p)
    m.commit(KEY, p, len(pay), zlib.crc32(pay))
    m.sink_done(KEY)
    m.checkpoint(3, 1 << 16)
    m.close()
    os.unlink(p)
    rep = M.recover(mpath, apply=True)
    assert rep.missing and KEY not in rep.done


# ----------------------------------------------------------------
# checkpoint hardening
# ----------------------------------------------------------------

def test_checkpoint_crc_and_bak_fallback(tmp_path):
    p = str(tmp_path / "ck.json")
    ck = StreamCheckpoint(p)
    ck.update(3, 1000)
    ck.update(4, 2000)
    assert os.path.exists(p + ".bak")
    # corrupt the primary: the previous generation takes over loudly
    with open(p, "w") as f:
        f.write('{"segments_done": 999999, "file_off')
    ck2 = StreamCheckpoint(p)
    assert ck2.segments_done == 3 and ck2.file_offset_bytes == 1000
    # corrupt BOTH: restart from 0, not from garbage
    with open(p + ".bak", "w") as f:
        f.write("not-json")
    ck3 = StreamCheckpoint(p)
    assert ck3.segments_done == 0


def test_checkpoint_crc_rejects_bitrot(tmp_path):
    p = str(tmp_path / "ck.json")
    StreamCheckpoint(p).update(7, 7000)
    with open(p) as f:
        state = json.load(f)
    state["segments_done"] = 9  # forged value, stale CRC
    with open(p, "w") as f:
        json.dump(state, f)
    ck = StreamCheckpoint(p)
    # primary rejected on CRC; .bak does not exist (single update)
    assert ck.segments_done == 0


def test_checkpoint_legacy_without_crc_accepted(tmp_path):
    p = str(tmp_path / "ck.json")
    with open(p, "w") as f:
        json.dump({"segments_done": 5, "file_offset_bytes": 500}, f)
    ck = StreamCheckpoint(p)
    assert ck.segments_done == 5 and ck.file_offset_bytes == 500


def test_checkpoint_seals_manifest_first(tmp_path):
    mpath = str(tmp_path / "m.jsonl")
    m = M.RunManifest.open(mpath)
    ck = StreamCheckpoint(str(tmp_path / "ck.json"), manifest=m)
    ck.update(2, 4096)
    m.close()
    scan = M.scan_manifest(mpath)
    last = scan.last_checkpoint
    assert last["segments_done"] == 2 and last["offset"] == 4096


# ----------------------------------------------------------------
# pipeline crash windows (in-process)
# ----------------------------------------------------------------

def _cfg(tmp_path, tag, n=1 << 12, segments=4, **kw):
    run_dir = tmp_path / tag
    run_dir.mkdir(exist_ok=True)
    return Config(
        baseband_input_count=n, baseband_input_bits=8,
        baseband_freq_low=1405.0, baseband_bandwidth=64.0,
        baseband_sample_rate=128e6, dm=0.05,
        input_file_path=str(tmp_path / "bb.bin"),
        baseband_output_file_prefix=str(run_dir / "out_"),
        spectrum_channel_count=1 << 4,
        mitigate_rfi_average_method_threshold=1000.0,
        mitigate_rfi_spectral_kurtosis_threshold=50.0,
        # below the noise floor: every segment writes (deterministic)
        signal_detect_signal_noise_threshold=2.0,
        signal_detect_max_boxcar_length=8,
        baseband_reserve_sample=False,
        writer_thread_count=0,
        inflight_segments=1,
        retry_max_attempts=1,
        checkpoint_path=str(run_dir / "ck.json"),
        run_manifest_path=str(run_dir / "manifest.jsonl"),
        **kw)


@pytest.fixture(scope="module")
def crash_env(tmp_path_factory):
    """Shared input file + pre-compiled processor + ONE golden output
    snapshot for the crash-window tests (deterministic timestamps make
    every run's artifact names identical, so one golden serves all)."""
    tmp_path = tmp_path_factory.mktemp("durability")
    n = 1 << 12
    segments = 4
    make_dispersed_baseband(
        n * segments, 1405.0, 64.0, 0.05,
        pulse_positions=[n // 2 + i * n for i in range(segments)],
        pulse_amp=30.0, nbits=8, seed=0,
    ).tofile(str(tmp_path / "bb.bin"))
    proc = SegmentProcessor(_cfg(tmp_path, "probe", n=n))
    golden_cfg = _cfg(tmp_path, "golden")
    _run_to_completion(golden_cfg, proc)
    golden = snapshot_outputs(_run_dir(golden_cfg))
    assert golden  # the equality gates must gate something
    return tmp_path, proc, n, segments, golden


def _run_to_completion(cfg, proc):
    metrics.reset()
    with Pipeline(cfg, source=make_resumable_source(cfg),
                  processor=proc) as pipe:
        stats = pipe.run()
    counters = {k: int(metrics.get(k)) for k in
                ("replayed_skips", "recovered_segments",
                 "rolled_back_intents")}
    metrics.reset()
    return stats, counters


def _run_dir(cfg):
    return os.path.dirname(cfg.baseband_output_file_prefix)


def test_crash_between_sink_commit_and_checkpoint(crash_env, tmp_path):
    """THE duplicate window: the run dies after segment 1's artifacts
    committed but before its checkpoint update.  The resume must skip
    the committed push (manifest done-set) and the final output set
    must equal the golden run's exactly."""
    tmp, proc, n, segments, golden = crash_env
    cfg = _cfg(tmp, "crash_a", fault_plan="checkpoint:fatal@1")
    with pytest.raises(Exception):
        with Pipeline(cfg, source=make_resumable_source(cfg),
                      processor=proc) as pipe:
            pipe.run()
    metrics.reset()
    resumed_cfg = cfg.replace(fault_plan="")
    stats, counters = _run_to_completion(resumed_cfg, proc)
    assert counters["replayed_skips"] >= 1
    assert counters["recovered_segments"] >= 1
    assert snapshot_outputs(_run_dir(cfg)) == golden


def test_crash_during_checkpoint_flush(crash_env, tmp_path):
    """The manifest ckpt record lands, then the process dies inside
    the state-file flush (tmp written, rename never happens): the
    resume repeats one segment, idempotently."""
    tmp, proc, n, segments, golden = crash_env
    cfg = _cfg(tmp, "crash_b")

    class Boom(RuntimeError):
        pass

    metrics.reset()
    with Pipeline(cfg, source=make_resumable_source(cfg),
                  processor=proc) as pipe:
        real_update = pipe.checkpoint.update
        calls = [0]

        def dying_update(segments_done, offset):
            calls[0] += 1
            if calls[0] == 2:  # die mid-flush of segment 1's update
                pipe.checkpoint.manifest.checkpoint(segments_done,
                                                    offset)
                with open(pipe.checkpoint.path + ".tmp", "w") as f:
                    f.write('{"segments_done":')  # torn tmp
                raise Boom("simulated death inside checkpoint flush")
            return real_update(segments_done, offset)

        pipe.checkpoint.update = dying_update
        with pytest.raises(Boom):
            pipe.run()
    stats, counters = _run_to_completion(cfg, proc)
    assert counters["replayed_skips"] >= 1
    assert snapshot_outputs(_run_dir(cfg)) == golden


def test_crash_mid_sink_write_rolls_back(crash_env, tmp_path):
    """Death between a temp write and its rename: recovery removes the
    orphan + uncommitted intent and the resume regenerates the
    artifact — exactly once."""
    from srtb_tpu.io import writers
    tmp, proc, n, segments, golden = crash_env
    cfg = _cfg(tmp, "crash_c")

    class Dead(BaseException):
        """Not Exception: nothing may 'handle' the simulated kill."""

    count = [0]

    def hook(path):
        count[0] += 1
        if count[0] == 3:
            raise Dead(path)

    writers._PRE_RENAME_HOOK = hook
    try:
        with pytest.raises(BaseException):
            with Pipeline(cfg, source=make_resumable_source(cfg),
                          processor=proc) as pipe:
                pipe.run()
    finally:
        writers._PRE_RENAME_HOOK = None
    stats, counters = _run_to_completion(cfg, proc)
    assert counters["rolled_back_intents"] >= 1
    assert snapshot_outputs(_run_dir(cfg)) == golden


def test_crash_replay_any_prefix_property(crash_env, tmp_path):
    """Seeded property: crash at ANY (site, segment) point, resume,
    and the final output set equals the golden run exactly once."""
    tmp, proc, n, segments, golden = crash_env
    rng = np.random.default_rng(7)
    sites = ("checkpoint", "sink_write", "dispatch", "fetch")
    for trial in range(3):
        site = sites[int(rng.integers(len(sites)))]
        seg = int(rng.integers(0, segments))
        tag = f"prop_{trial}"
        cfg = _cfg(tmp, tag, fault_plan=f"{site}:fatal@{seg}")
        with pytest.raises(Exception):
            with Pipeline(cfg, source=make_resumable_source(cfg),
                          processor=proc) as pipe:
                pipe.run()
        _run_to_completion(cfg.replace(fault_plan=""), proc)
        assert snapshot_outputs(_run_dir(cfg)) == golden, \
            f"trial {trial}: crash at {site}@{seg} broke exactly-once"


def test_write_all_exactly_once_across_crash(crash_env, tmp_path):
    """The in-place appender: a crash between the append's commit and
    the checkpoint must not double-append on resume."""
    tmp, proc, n, segments, _golden = crash_env
    golden_cfg = _cfg(tmp, "golden_w", baseband_write_all=True)
    _run_to_completion(golden_cfg, proc)
    golden = snapshot_outputs(_run_dir(golden_cfg))
    stream = [k for k in golden if k.startswith("out_stream")]
    assert stream  # the appender actually wrote

    cfg = _cfg(tmp, "crash_w", baseband_write_all=True,
               fault_plan="checkpoint:fatal@2")
    with pytest.raises(Exception):
        with Pipeline(cfg, source=make_resumable_source(cfg),
                      processor=proc) as pipe:
            pipe.run()
    stats, counters = _run_to_completion(cfg.replace(fault_plan=""),
                                         proc)
    assert counters["replayed_skips"] >= 1
    assert snapshot_outputs(_run_dir(cfg)) == golden


# ----------------------------------------------------------------
# fsck
# ----------------------------------------------------------------

def test_fsck_clean_run_and_corruptions(crash_env, tmp_path):
    tmp, proc, n, segments, _golden = crash_env
    cfg = _cfg(tmp, "fsck_run")
    _run_to_completion(cfg, proc)
    mpath = cfg.run_manifest_path
    ckpath = cfg.checkpoint_path
    rep = F.fsck(mpath, ckpath)
    assert rep["clean"], rep

    assert F.main([mpath, "--checkpoint", ckpath]) == F.EXIT_CLEAN

    # delete a committed artifact -> exit 1
    run_dir = _run_dir(cfg)
    victim = next(os.path.join(run_dir, f)
                  for f in sorted(os.listdir(run_dir))
                  if f.endswith(".bin") and "stream" not in f)
    os.rename(victim, victim + ".hidden")
    assert F.main([mpath, "--checkpoint", ckpath]) == F.EXIT_ERRORS
    os.rename(victim + ".hidden", victim)

    # checkpoint ahead of manifest -> exit 1; --repair heals it
    StreamCheckpoint(ckpath).update(10 ** 6, 10 ** 9)
    assert F.main([mpath, "--checkpoint", ckpath]) == F.EXIT_ERRORS
    assert F.main([mpath, "--checkpoint", ckpath, "--repair"]) \
        == F.EXIT_CLEAN
    assert F.main([mpath, "--checkpoint", ckpath]) == F.EXIT_CLEAN

    # missing manifest -> exit 2
    assert F.main([str(tmp_path / "nope.jsonl")]) == F.EXIT_UNVERIFIABLE


def test_fsck_repair_truncates_torn_wal(crash_env, tmp_path):
    tmp, proc, n, segments, _golden = crash_env
    cfg = _cfg(tmp, "fsck_torn")
    _run_to_completion(cfg, proc)
    with open(cfg.run_manifest_path, "ab") as f:
        f.write(b'{"t":"ckpt","half')
    assert F.main([cfg.run_manifest_path]) == F.EXIT_ERRORS
    assert F.main([cfg.run_manifest_path, "--repair"]) == F.EXIT_CLEAN


def test_fsck_selftest_is_sharp():
    assert F.selftest() == []


# ----------------------------------------------------------------
# writer-pool commit hook + telemetry v5
# ----------------------------------------------------------------

def test_py_pool_fires_on_done_after_write(tmp_path):
    from srtb_tpu.io.native_writer import AsyncWriterPool
    pool = AsyncWriterPool(2, prefer_native=False)
    fired = []
    p = str(tmp_path / "x.bin")
    pool.submit(p, b"payload", on_done=lambda: fired.append(p))
    pool.drain()
    assert fired == [p] and os.path.exists(p)
    # a FAILING write must not commit
    bad = str(tmp_path / "no_dir" / "y.bin")
    pool.submit(bad, b"payload", on_done=lambda: fired.append(bad))
    pool.drain()
    assert fired == [p]
    with pytest.raises(RuntimeError):
        pool.raise_new_errors("test")
    pool.close()


def test_telemetry_v5_and_report(crash_env, tmp_path):
    from srtb_tpu.tools import telemetry_report as TR
    from srtb_tpu.utils.telemetry import SPAN_SCHEMA_VERSION
    assert SPAN_SCHEMA_VERSION == 11
    tmp, proc, n, segments, _golden = crash_env
    journal = str(tmp_path / "j.jsonl")
    cfg = _cfg(tmp, "tele", telemetry_journal_path=journal)
    _run_to_completion(cfg, proc)
    recs = TR.load(journal)
    assert recs
    for r in recs:
        assert r["v"] == 11
        for k in ("recovered_segments", "replayed_skips",
                  "rolled_back_intents"):
            assert k in r, (k, r)
    rep = TR.report(journal)
    assert rep["durability"]["replayed_skips"] == 0
    # mixed v4/v5: old records without the fields still summarize
    with open(journal, "a") as f:
        f.write(json.dumps({"type": "segment_span", "v": 4,
                            "ts": recs[-1]["ts"] + 1.0, "segment": 99,
                            "stages_ms": {"sink": 1.0},
                            "degrade_level": 0, "retries": 0}) + "\n")
    rep2 = TR.report(journal)
    assert rep2["records"] == len(recs) + 1
    assert rep2["durability"]["records"] == len(recs)


# ----------------------------------------------------------------
# the real thing (slow): SIGKILL subprocess soak
# ----------------------------------------------------------------

@pytest.mark.slow
def test_sigkill_crash_soak_two_kills():
    from srtb_tpu.tools.crash_soak import run_soak
    report = run_soak(seed=1, segments=5, log2n=12,
                      kill_plan="ckpt_stall@1,rename@1")
    assert report["ok"] and report["sigkills"] == 2
    assert report["replayed_skips"] >= 1
    assert report["rolled_back_intents"] >= 1
