"""Writer tests: the async writer pool (native C++ + Python fallback) and
the candidate sink's piggybank policy / file formats.

Oracle style mirrors the reference's (SURVEY.md §4): byte-level comparison
against synchronously-written files and hand-computed expectations.
"""

import os

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.io import native_writer
from srtb_tpu.io.native_writer import AsyncWriterPool
from srtb_tpu.io.writers import WriteSignalSink
from srtb_tpu.ops.detect import DetectResult
from srtb_tpu.pipeline.work import SegmentResultWork, SegmentWork


@pytest.fixture(params=["native", "python"])
def pool(request):
    if request.param == "native" and not native_writer.native_available():
        pytest.skip("libsrtb_writer.so not built")
    p = AsyncWriterPool(n_threads=3,
                        prefer_native=(request.param == "native"))
    assert p.is_native == (request.param == "native")
    yield p
    p.close()


def test_pool_writes_bytes_and_arrays(pool, tmp_path):
    rng = np.random.default_rng(0)
    blobs = {str(tmp_path / f"f{i}.bin"): rng.integers(
        0, 256, size=rng.integers(1, 4096), dtype=np.uint8)
        for i in range(16)}
    for path, blob in blobs.items():
        pool.submit(path, blob, fsync=(hash(path) % 2 == 0))
    pool.drain()
    for path, blob in blobs.items():
        with open(path, "rb") as f:
            assert f.read() == blob.tobytes()
    stats = pool.stats()
    assert stats["jobs_done"] == len(blobs)
    assert stats["errors"] == 0
    assert stats["bytes_written"] == sum(b.size for b in blobs.values())


def test_pool_caller_buffer_reusable(pool, tmp_path):
    # submission copies: mutating the source after submit must not change
    # what lands on disk (the reference passes shared_ptr-owned copies)
    buf = np.full(1 << 16, 7, dtype=np.uint8)
    path = str(tmp_path / "reuse.bin")
    pool.submit(path, buf)
    buf[:] = 0
    pool.drain()
    assert np.all(np.fromfile(path, dtype=np.uint8) == 7)


def test_pool_append_single_thread(tmp_path):
    # ordered appends need a 1-thread pool (like the reference's dedicated
    # per-purpose pools)
    for native in ([True] if native_writer.native_available() else []) + [False]:
        p = AsyncWriterPool(n_threads=1, prefer_native=native)
        path = str(tmp_path / f"append_{native}.bin")
        for i in range(8):
            p.submit(path, np.full(4, i, dtype=np.uint8), append=True)
        p.drain()
        got = np.fromfile(path, dtype=np.uint8)
        assert got.tolist() == sum(([i] * 4 for i in range(8)), [])
        p.close()
    # append on a multi-thread pool would reorder: must be rejected
    with AsyncWriterPool(n_threads=2, prefer_native=False) as p:
        with pytest.raises(ValueError):
            p.submit(str(tmp_path / "bad.bin"), b"x", append=True)


def test_write_all_sink_async(tmp_path):
    from srtb_tpu.io.writers import WriteAllSink
    cfg = _mk_cfg(tmp_path, "writeall")
    with AsyncWriterPool(n_threads=1) as pool:
        sink = WriteAllSink(cfg, reserved_bytes=64, writer_pool=pool)
        works = [_mk_work(counter=i) for i in range(4)]
        for w in works:
            sink.push(w)
        sink.drain()
        expected = b"".join(
            np.ascontiguousarray(w.segment.data[:-64]).tobytes()
            for w in works)
        with open(sink.path, "rb") as f:
            assert f.read() == expected
    with pytest.raises(ValueError):
        WriteAllSink(cfg, 0, writer_pool=AsyncWriterPool(
            n_threads=2, prefer_native=False))


def test_pool_backpressure_bounded_queue(tmp_path):
    # with a tiny byte bound, submit must block-and-release rather than
    # deadlock or drop jobs (the reference's bounded-queue backpressure)
    for native in ([True] if native_writer.native_available() else []) + [False]:
        p = AsyncWriterPool(n_threads=2, prefer_native=native,
                            max_queued_bytes=1 << 12)
        blob = np.arange(1 << 10, dtype=np.uint8) % 251
        for i in range(64):  # 64 KiB through a 4 KiB window
            p.submit(str(tmp_path / f"bp_{native}_{i}.bin"), blob)
        big = np.full(1 << 14, 3, dtype=np.uint8)  # oversized single job
        p.submit(str(tmp_path / f"bp_{native}_big.bin"), big)
        p.drain()
        assert p.stats()["jobs_done"] == 65
        assert p.stats()["errors"] == 0
        got = np.fromfile(str(tmp_path / f"bp_{native}_63.bin"),
                          dtype=np.uint8)
        assert np.array_equal(got, blob)
        p.close()


def test_pool_error_accounting(pool, tmp_path):
    pool.submit(str(tmp_path / "no" / "such" / "dir" / "x.bin"),
                np.zeros(4, dtype=np.uint8))
    pool.drain()
    assert pool.stats()["errors"] == 1
    with pytest.raises(RuntimeError, match="1 async write"):
        pool.raise_new_errors("test")
    pool.raise_new_errors("test")  # already reported: no raise


def test_signal_sink_drain_raises_on_failed_write(tmp_path):
    cfg = _mk_cfg(tmp_path, "errs")
    with AsyncWriterPool(n_threads=1) as pool:
        sink = WriteSignalSink(cfg, fdatasync=False, writer_pool=pool)
        sink.push(_mk_work(), has_signal=True)
        sink.drain()  # fine
        import shutil
        shutil.rmtree(os.path.dirname(cfg.baseband_output_file_prefix))
        sink.push(_mk_work(counter=99), has_signal=True)
        with pytest.raises(RuntimeError, match="async write"):
            sink.drain()


# ----------------------------------------------------------------------
# WriteSignalSink with an async pool must produce byte-identical files to
# the synchronous path.
# ----------------------------------------------------------------------

def _mk_cfg(tmp_path, name):
    d = tmp_path / name
    d.mkdir()
    return Config(
        baseband_input_count=1 << 10, baseband_input_bits=8,
        baseband_format_type="simple", baseband_freq_low=1000.0,
        baseband_bandwidth=16.0, baseband_sample_rate=32e6, dm=5.0,
        spectrum_channel_count=1 << 4,
        baseband_output_file_prefix=str(d) + "/cand_")


def _mk_work(counter=1234):
    rng = np.random.default_rng(42)
    seg = SegmentWork(
        data=rng.integers(0, 256, size=1 << 10, dtype=np.uint8),
        timestamp=10 ** 15, udp_packet_counter=counter)
    wf = (rng.normal(size=(1, 16, 32)) +
          1j * rng.normal(size=(1, 16, 32))).astype(np.complex64)
    t = 32
    detect = DetectResult(
        zero_count=np.int32(0),
        time_series=rng.normal(size=(1, t)).astype(np.float32),
        boxcar_lengths=(1, 2, 4),
        signal_counts=np.array([[3, 0, 1]], dtype=np.int32),
        boxcar_series=rng.normal(size=(1, 3, t)).astype(np.float32),
        snr_peaks=np.array([[9.0, 1.0, 8.5]], dtype=np.float32))
    return SegmentResultWork(segment=seg, waterfall=wf, detect=detect)


def test_signal_sink_async_matches_sync(tmp_path):
    work = _mk_work()

    sync_sink = WriteSignalSink(_mk_cfg(tmp_path, "sync"), fdatasync=False)
    sync_sink.push(work, has_signal=True)

    with AsyncWriterPool(n_threads=2) as pool:
        async_sink = WriteSignalSink(_mk_cfg(tmp_path, "async"),
                                     fdatasync=False, writer_pool=pool)
        async_sink.push(work, has_signal=True)
        async_sink.drain()

    assert len(sync_sink.written) == len(async_sink.written) == 1
    s, a = sync_sink.written[0], async_sink.written[0]
    for sp, ap in zip([s.bin_path] + s.npy_paths + s.tim_paths,
                      [a.bin_path] + a.npy_paths + a.tim_paths):
        with open(sp, "rb") as f1, open(ap, "rb") as f2:
            assert f1.read() == f2.read(), (sp, ap)
    # npy round-trip sanity: plot_spectrum.py-compatible payload
    arr = np.load(a.npy_paths[0])
    assert arr.dtype == np.complex64 and arr.shape == (16, 32)


def test_signal_sink_async_npy_index_collision(tmp_path):
    # queued-but-unwritten .npy paths must count as taken when picking the
    # next free index (ref picks first non-existing name, 230-235)
    cfg = _mk_cfg(tmp_path, "collide")
    with AsyncWriterPool(n_threads=1) as pool:
        sink = WriteSignalSink(cfg, fdatasync=False, writer_pool=pool)
        sink.push(_mk_work(counter=7), has_signal=True)
        sink.push(_mk_work(counter=7), has_signal=True)  # same counter
        sink.drain()
    paths = sorted(p for w in sink.written for p in w.npy_paths)
    assert len(paths) == len(set(paths)) == 2


def test_piggybank_other_polarization_capture(tmp_path):
    # a negative segment whose timestamp overlaps (±0.45 segment) a recent
    # positive must still be written (ref: write_signal_pipe.hpp:102-115);
    # piggybank applies in real-time (UDP) mode only
    cfg = _mk_cfg(tmp_path, "piggy")
    assert cfg.input_file_path == ""
    sink = WriteSignalSink(cfg, fdatasync=False)
    seg_ns = 1e9 * cfg.baseband_input_count / cfg.baseband_sample_rate

    pos = _mk_work(counter=100)
    sink.push(pos, has_signal=True)
    near = _mk_work(counter=101)
    near.segment.timestamp = pos.segment.timestamp + int(0.2 * seg_ns)
    sink.push(near, has_signal=False)
    far = _mk_work(counter=102)
    far.segment.timestamp = pos.segment.timestamp + int(10 * seg_ns)
    sink.push(far, has_signal=False)

    counters = [os.path.basename(w.bin_path) for w in sink.written]
    assert counters == ["cand_100.bin", "cand_101.bin"]
