"""Plan-family registry invariants (pipeline/registry.py).

The contract: plan families are DATA in one table, and every consumer
— ``segment.py`` plan construction, ``demote.py``'s ladder,
``hlo_audit.py``'s auditable specs, ``fleet.py``'s shared plan cache —
enumerates from that table alone.  A family added to only one consumer
must fail here; the four source files must contain no independent
family lists (grep-provable, pinned below)."""

import json
import os
import re

import pytest

from srtb_tpu.analysis import hlo_audit as HA
from srtb_tpu.config import Config
from srtb_tpu.pipeline import registry
from srtb_tpu.resilience.demote import (LADDER_ORDER, ladder_rungs,
                                        parse_ladder)

SRC = os.path.join(os.path.dirname(__file__), "..", "srtb_tpu")


def _read(rel):
    with open(os.path.join(SRC, rel)) as f:
        return f.read()


# ------------------------------------------------------------------
# round-trip: registry <-> plan cards <-> ladder, no orphans


def test_every_family_has_a_checked_in_card_and_vice_versa():
    """registry -> plan_cards.json and back, no orphans in either
    direction: a family registered but never carded (or a card whose
    family was dropped) fails CI before a human ever greps."""
    baseline = HA.CardBaseline.load(HA.DEFAULT_BASELINE)
    assert baseline.cards, "checked-in plan_cards.json missing/empty"
    keys = set(registry.plan_keys())
    carded = set(baseline.cards)
    assert keys - carded == set(), \
        f"registered families without a plan card: {keys - carded}"
    assert carded - keys == set(), \
        f"plan cards without a registered family: {carded - keys}"


def test_card_mode_matches_registered_mode():
    baseline = HA.CardBaseline.load(HA.DEFAULT_BASELINE)
    for key, card in baseline.cards.items():
        fam = registry.family(key)
        assert fam is not None
        assert card.get("mode") == fam.mode, (key, card.get("mode"))


def test_family_roundtrip_key_signature_consistency():
    """Equal plan_cache_keys + equal constructor overrides imply
    equal plan_signatures across the WHOLE registered zoo.  The
    cache key is a config-only projection; the ``staged`` audit
    override is a constructor input the fleet never passes
    (SharedPlanCache builds with staged=None), so the fleet-safety
    claim is keyed on (cache_key, staged override) here — families
    differing ONLY in the override (e.g. four_step_ftail_donate vs
    staged) legitimately share a config key while the fleet can only
    ever reach the staged=None member."""
    by_key = {}
    for spec in registry.plan_families():
        cfg = HA._audit_config(HA.DEFAULT_LOG2N, HA.DEFAULT_CHANNELS,
                               dict(spec.cfg))
        with HA._env(spec.env):
            cache_key = registry.plan_cache_key(
                cfg, donate_input=spec.donate)
            proc = registry.build_processor(
                cfg, staged=spec.staged, donate_input=spec.donate)
            sig = proc.plan_signature()
        seen = by_key.setdefault((cache_key, spec.staged),
                                 (spec.key, sig))
        assert seen[1] == sig, \
            (f"families {seen[0]} and {spec.key} share a cache key "
             "but resolve different plan signatures")
        # declared floor must match what the built plan reports
        if spec.hbm_passes is not None:
            assert proc.hbm_passes == spec.hbm_passes, spec.key
        # the mode's processor class really implements the mode
        assert proc.MODE == spec.mode, spec.key


def test_ladder_order_comes_from_registry():
    assert LADDER_ORDER == registry.ladder_order()
    assert parse_ladder("auto") == registry.ladder_order()
    with pytest.raises(ValueError):
        parse_ladder("warp_drive")


def test_every_ladder_rung_lands_on_an_eligible_carded_family():
    """The full ladder walk from the fully-featured audit config:
    every rung fingerprint-matches a checked-in card whose registered
    family is ladder-ELIGIBLE (audit_ladder is the CI gate; this
    pins it in the suite too)."""
    baseline = HA.CardBaseline.load(HA.DEFAULT_BASELINE)
    assert HA.audit_ladder(baseline) == []


def test_ladder_sheds_periodicity_first_and_never_enters_it():
    cfg = HA._audit_config(HA.DEFAULT_LOG2N, HA.DEFAULT_CHANNELS,
                           dict(HA.LADDER_AUDIT_CFG))
    rungs = ladder_rungs(cfg)
    assert rungs[0].step == "search_mode"
    assert rungs[0].cfg.search_mode == "single_pulse"
    # every subsequent rung stays single-pulse
    for rung in rungs[1:]:
        assert rung.cfg.search_mode == "single_pulse", rung.step
    # the periodicity families are registered ladder-INELIGIBLE
    for key in ("periodicity_ftail", "periodicity_ring_mb2"):
        assert registry.family(key).ladder is False


def test_family_added_to_only_one_consumer_fails():
    """A temp family registered WITHOUT a card surfaces as
    unbaselined in the audit diff (the plan_audit CI gate) — adding a
    family is not done until its card is accepted."""
    baseline = HA.CardBaseline.load(HA.DEFAULT_BASELINE)
    with registry.temp_family(registry.PlanFamily(
            key="__test_orphan", desc="t",
            cfg={"fft_strategy": "four_step", "fused_tail": "on"},
            donate=True, hbm_passes=5)):
        assert "__test_orphan" in registry.plan_keys()
        assert "__test_orphan" in tuple(s.key for s in HA.PLAN_FAMILIES)
        cards = HA.audit_families(["__test_orphan"])
        _, new_plans, _ = HA.diff_cards(cards, baseline)
        assert new_plans == ["__test_orphan"]
    assert "__test_orphan" not in registry.plan_keys()


# ------------------------------------------------------------------
# search modes


def test_mode_dispatch_and_unknown_mode():
    cfg = HA._audit_config(HA.DEFAULT_LOG2N, HA.DEFAULT_CHANNELS, {})
    assert registry.resolve_mode(cfg).name == "single_pulse"
    p = registry.build_processor(cfg)
    assert p.MODE == "single_pulse"
    cfg_p = cfg.replace(search_mode="periodicity")
    assert registry.build_processor(cfg_p).MODE == "periodicity"
    with pytest.raises(ValueError, match="unknown search_mode"):
        registry.build_processor(cfg.replace(search_mode="nope"))


def test_cache_key_distinguishes_modes_and_keys_are_json():
    cfg = HA._audit_config(HA.DEFAULT_LOG2N, HA.DEFAULT_CHANNELS, {})
    k1 = registry.plan_cache_key(cfg)
    k2 = registry.plan_cache_key(cfg.replace(search_mode="periodicity"))
    assert k1 != k2
    assert json.loads(k1)["mode"] == "single_pulse"
    assert json.loads(k2)["mode"] == "periodicity"
    # tenancy stays outside the key (the fleet claim, both modes)
    k3 = registry.plan_cache_key(cfg.replace(
        search_mode="periodicity", stream_name="s7",
        stream_priority=3))
    assert k2 == k3


def test_periodicity_knobs_split_the_cache_key():
    cfg = HA._audit_config(HA.DEFAULT_LOG2N, HA.DEFAULT_CHANNELS,
                           {"search_mode": "periodicity"})
    k1 = registry.plan_cache_key(cfg)
    k2 = registry.plan_cache_key(
        cfg.replace(periodicity_candidates=8))
    assert k1 != k2
    # ...but NOT the single-pulse key (the knobs are dead there)
    s1 = registry.plan_cache_key(
        cfg.replace(search_mode="single_pulse"))
    s2 = registry.plan_cache_key(
        cfg.replace(search_mode="single_pulse",
                    periodicity_candidates=8))
    assert s1 == s2


def test_duplicate_registration_rejected():
    with pytest.raises(ValueError, match="already registered"):
        registry.register_mode(registry.SearchMode(
            "single_pulse", "dup", "x:y"))
    with pytest.raises(ValueError, match="already registered"):
        registry.register_step(registry.LadderStep(
            "ring", "dup", lambda c, s: None))
    with pytest.raises(ValueError, match="already registered"):
        with registry.temp_family(registry.PlanFamily(
                key="monolithic", desc="dup")):
            pass


def test_family_with_unregistered_mode_rejected():
    with pytest.raises(ValueError, match="unregistered mode"):
        registry.register_family(registry.PlanFamily(
            key="__bad_mode", desc="t", mode="nope"))


# ------------------------------------------------------------------
# grep-provable: no independent family lists in the consumers


def test_consumers_hold_no_independent_family_lists():
    """The four consumers enumerate from the registry alone.  Pinned
    by source inspection: the old literal tables and mirrored rule
    chains must not reappear."""
    hlo = _read("analysis/hlo_audit.py")
    assert "PLAN_FAMILIES = (" not in hlo
    assert "PlanSpec(\"" not in hlo and "PlanSpec('" not in hlo
    assert "registry.plan_families()" in hlo
    assert "registry.plan_keys()" in hlo

    demote = _read("resilience/demote.py")
    # the canonical order is READ from the registry, never restated
    assert re.search(r"LADDER_ORDER\s*=\s*\(", demote) is None
    assert "registry.ladder_order()" in demote
    # no per-step rule chain left behind
    assert '== "micro_batch"' not in demote
    assert '== "monolithic"' not in demote
    assert "registry.ladder_step(" in demote

    fleet = _read("pipeline/fleet.py")
    assert "registry.plan_cache_key(" in fleet
    assert "registry.build_processor(" in fleet
    assert "SegmentProcessor.plan_cache_key(" not in fleet
    assert re.search(r"SegmentProcessor\(\s*cfg", fleet) is None

    runtime = _read("pipeline/runtime.py")
    assert "registry.build_processor(" in runtime


def test_tools_enumerate_from_registry():
    # the plan_audit CLI lists families through hlo_audit's live view
    src = _read("tools/plan_audit.py")
    assert "PLAN_FAMILIES = (" not in src


def test_config_knobs_registered_in_field_sets():
    """The new knobs parse from config files / CLI like every other
    option (a field missing from the typed sets silently becomes a
    string)."""
    cfg = Config()
    assert cfg.set_option("search_mode", "periodicity")
    assert cfg.search_mode == "periodicity"
    assert cfg.set_option("periodicity_harmonics", "2 ** 3")
    assert cfg.periodicity_harmonics == 8
    assert cfg.set_option("periodicity_snr_threshold", "7.5")
    assert cfg.periodicity_snr_threshold == 7.5
    assert cfg.set_option("deterministic_timestamps", "1")
    assert cfg.deterministic_timestamps is True
    assert cfg.set_option("periodicity_fold_bins", "32")
    assert cfg.periodicity_fold_bins == 32
