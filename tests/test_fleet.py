"""Multi-tenant stream fleet tests (pipeline/fleet.py +
resilience/admission.py + the cross-stream fairness policy).

The contract under test is the bulkhead: N streams on one device,
one faulty tenant, blast radius exactly itself —
- victim OOM demotes the victim's plan only; healthy streams' outputs
  stay bit-identical to their solo single-stream runs;
- a wedged victim sink sheds the victim's segments as accounted
  per-stream loss while healthy streams finish untouched;
- a victim manifest rollback (crash debris) is recovered in the
  victim's namespace only;
- a device HALT is the one shared domain: one budgeted fleet reinit,
  every stream completes with decisions intact;
- the shared plan cache compiles each plan family exactly once
  (second stream of a family compiles nothing);
- admission control rejects/queues over capacity in priority order,
  and the fleet shed policy sheds lowest-priority real-time streams
  first with hysteresis;
- per-stream observability: ``stream``-labeled metrics, v7 journal
  attribution, per-stream /healthz staleness, mixed v5/v6/v7 reports.
"""

import json
import os
import time

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.pipeline.fleet import (SharedPlanCache, StreamFleet,
                                     StreamSpec)
from srtb_tpu.pipeline.runtime import Pipeline
from srtb_tpu.pipeline.work import SegmentWork
from srtb_tpu.resilience.admission import (ADMIT, QUEUE, REJECT,
                                           AdmissionController)
from srtb_tpu.resilience.degrade import FleetShedPolicy
from srtb_tpu.resilience.faults import FaultInjector, parse_plan
from srtb_tpu.utils import telemetry
from srtb_tpu.utils.metrics import metrics

N = 1 << 13
SEGMENTS = 4


@pytest.fixture(autouse=True)
def _fresh_metrics():
    metrics.reset()
    yield
    metrics.reset()


def _mkcfg(tmp, tag, infile, **kw):
    base = dict(
        baseband_input_count=N, baseband_input_bits=8,
        baseband_freq_low=1405.0, baseband_bandwidth=64.0,
        baseband_sample_rate=128e6, dm=0.05,
        input_file_path=infile,
        baseband_output_file_prefix=os.path.join(str(tmp), tag + "_"),
        spectrum_channel_count=64,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        baseband_reserve_sample=True,
        writer_thread_count=0, fft_strategy="four_step",
        inflight_segments=2, retry_backoff_base_s=0.001)
    base.update(kw)
    return Config(**base)


def _make_bb(tmp, tag, seed):
    path = os.path.join(str(tmp), f"bb_{tag}.bin")
    make_dispersed_baseband(
        N * SEGMENTS, 1405.0, 64.0, 0.05,
        pulse_positions=[N // 2 + j * N for j in range(SEGMENTS)],
        pulse_amp=30.0, nbits=8, seed=seed).tofile(path)
    return path


class _Cap:
    """Decision-capturing sink."""

    def __init__(self):
        self.out = []

    def push(self, work, positive):
        det = work.detect
        self.out.append((np.asarray(det.signal_counts).copy(),
                         np.asarray(det.zero_count).copy(),
                         np.asarray(det.time_series).copy(),
                         bool(positive)))


def _solo(cfg):
    cap = _Cap()
    with Pipeline(cfg, sinks=[cap]) as pipe:
        stats = pipe.run()
    return stats, cap.out


def _decisions_equal(a, b, ts_exact=True):
    for i, (x, y) in enumerate(zip(a, b)):
        assert np.array_equal(x[0], y[0]), f"signal_counts @ {i}"
        assert np.array_equal(x[1], y[1]), f"zero_count @ {i}"
        if ts_exact:
            assert np.array_equal(x[2], y[2]), f"time_series @ {i}"
        assert x[3] == y[3], f"positive @ {i}"
    assert len(a) == len(b)


# ------------------------------------------------- fault stream scope


def test_fault_plan_stream_selector_parses():
    specs = parse_plan("stream0:dispatch:oom@3,ingest:raise@1,"
                       "beam2:fetch:stall=0.5@2")
    assert specs[0].stream == "stream0" and specs[0].site == "dispatch"
    assert specs[1].stream is None
    assert specs[2].stream == "beam2" and specs[2].arg == 0.5
    assert str(specs[0]) == "stream0:dispatch:oom@3"


def test_fault_injector_scopes_by_stream():
    plan = "stream0:dispatch:oom@3,ingest:raise@1"
    fi = FaultInjector.from_plan(plan, stream="stream1")
    assert not fi.armed("dispatch") and fi.armed("ingest")
    fi = FaultInjector.from_plan(plan, stream="stream0")
    assert fi.armed("dispatch") and fi.armed("ingest")
    # unnamed (solo) pipeline: selector entries never arm; a plan
    # that is ALL selectors degrades to None (zero-cost off)
    assert FaultInjector.from_plan("s0:dispatch:oom@1", stream="") \
        is None


def test_fault_plan_without_selector_unchanged():
    # legacy plans parse exactly as before (satellite contract)
    specs = parse_plan("ingest:raise@1,fetch:stall=0.5@2")
    assert all(s.stream is None for s in specs)
    fi = FaultInjector.from_plan("ingest:raise@1", stream="anything")
    assert fi.armed("ingest")


# -------------------------------------------------- admission control


def test_admission_capacity_queue_reject_priority():
    adm = AdmissionController(max_streams=2, queue_limit=1)
    assert adm.request("a", 0) == ADMIT
    assert adm.request("b", 0) == ADMIT
    assert adm.request("c", 1) == QUEUE
    # queue full: lower-priority newcomer rejected outright
    assert adm.request("d", 0) == REJECT
    assert adm.rejected == ["d"]
    # higher-priority newcomer evicts the queued lower one
    assert adm.request("e", 5) == QUEUE
    assert adm.rejected == ["d", "c"]
    assert adm.queued == ["e"]
    # release frees a slot: highest-priority queued stream pops
    adm.release("a")
    assert adm.pop_ready() == "e"
    assert adm.pop_ready() is None
    assert metrics.get("fleet_rejected") == 2
    assert metrics.get("fleet_admitted", labels={"stream": "e"}) == 1


def test_admission_unlimited_by_default():
    adm = AdmissionController(max_streams=0)
    assert all(adm.request(f"s{i}", 0) == ADMIT for i in range(10))


# ------------------------------------------------ fleet shed ordering


def test_fleet_shed_priority_order_and_hysteresis():
    pol = FleetShedPolicy(high=0.9, low=0.25, hold=2)
    lanes = [("hi", 5, True), ("mid", 3, True), ("lo", 1, True),
             ("file", 0, False)]
    assert pol.observe(1.0, False, lanes) == set()      # hold=2
    assert pol.observe(1.0, False, lanes) == {"lo"}     # lowest prio
    assert pol.observe(1.0, False, lanes) == set() or True
    pol.observe(1.0, False, lanes)
    # next shed takes the next-lowest REAL-TIME stream ("file" is
    # file-mode and never shed)
    assert "mid" in pol.shed and "file" not in pol.shed
    # relief restores highest priority first
    pol.observe(0.0, False, lanes)
    assert pol.observe(0.0, False, lanes) <= {"lo"}
    assert "mid" not in pol.shed
    assert metrics.get("fleet_sheds", labels={"stream": "lo"}) == 1


# ------------------------------------------- backpressure attribution


def test_drop_oldest_attributes_stream():
    import threading

    class SlowSource:
        pool = None

        def __iter__(self):
            for i in range(6):
                yield SegmentWork(data=np.zeros(4, np.uint8),
                                  data_stream_id=i % 2, seq=i)

    from srtb_tpu.io.backpressure import DropOldestSegmentBuffer
    buf = DropOldestSegmentBuffer(SlowSource(), capacity=1,
                                  name="t_attr")
    # let the pump overrun the capacity before consuming
    deadline = time.time() + 5
    while buf.dropped < 2 and time.time() < deadline:
        time.sleep(0.01)
    list(buf)
    buf.close()
    assert buf.dropped >= 2
    assert sum(buf.dropped_by_stream.values()) == buf.dropped
    per = metrics.by_label("segments_dropped")
    assert sum(per.values()) == buf.dropped
    assert set(per) <= {"0", "1"}
    # a named buffer attributes to its stream label instead
    metrics.reset()
    buf = DropOldestSegmentBuffer(SlowSource(), capacity=1,
                                  name="t_attr2", stream="beamX")
    deadline = time.time() + 5
    while buf.dropped < 1 and time.time() < deadline:
        time.sleep(0.01)
    list(buf)
    buf.close()
    assert set(buf.dropped_by_stream) == {"beamX"}
    assert metrics.get("segments_dropped",
                       labels={"stream": "beamX"}) == buf.dropped


# --------------------------------------------------- shared plan cache


def test_shared_plan_cache_key_ignores_tenancy(tmp_path):
    bb = _make_bb(tmp_path, "k", 0)
    from srtb_tpu.pipeline.segment import SegmentProcessor
    a = _mkcfg(tmp_path, "a", bb, stream_name="a", stream_priority=1,
               checkpoint_path=os.path.join(str(tmp_path), "a.ck"))
    b = _mkcfg(tmp_path, "b", bb, stream_name="b")
    assert SegmentProcessor.plan_cache_key(a) == \
        SegmentProcessor.plan_cache_key(b)
    c = _mkcfg(tmp_path, "c", bb, spectrum_channel_count=128)
    assert SegmentProcessor.plan_cache_key(a) != \
        SegmentProcessor.plan_cache_key(c)


def test_shared_plan_cache_compiles_once(tmp_path):
    bb = _make_bb(tmp_path, "p", 0)
    cache = SharedPlanCache()
    p1 = cache.get(_mkcfg(tmp_path, "a", bb))
    p2 = cache.get(_mkcfg(tmp_path, "b", bb))
    assert p1 is p2 and cache.compiles == 1 and cache.hits == 1
    assert p1._fleet_shared
    # retire() without force is a no-op on a shared plan
    p1.retire()
    assert p1._jit_process is not None and callable(p1._jit_process)
    # a different family compiles separately
    p3 = cache.get(_mkcfg(tmp_path, "c", bb,
                          spectrum_channel_count=128))
    assert p3 is not p1 and cache.compiles == 2
    cache.invalidate()
    with pytest.raises(RuntimeError, match="retired"):
        p1._jit_process(None)


# ------------------------------------------------------ fleet e2e


def test_fleet_matches_solo_and_shares_plan(tmp_path):
    bbs = {t: _make_bb(tmp_path, t, i)
           for i, t in enumerate(("s0", "s1"))}
    solo = {}
    for t, bb in bbs.items():
        metrics.reset()
        solo[t] = _solo(_mkcfg(tmp_path, t + "solo", bb))
    metrics.reset()
    caps = {t: _Cap() for t in bbs}
    fleet = StreamFleet([
        StreamSpec(name=t, cfg=_mkcfg(tmp_path, t, bb),
                   sinks=[caps[t]])
        for t, bb in bbs.items()])
    res = fleet.run()
    fleet.close()
    assert fleet.plans.compiles == 1 and fleet.plans.hits == 1
    for t in bbs:
        assert res[t].status == "done" and res[t].dropped == 0
        assert res[t].drained == solo[t][0].segments
        _decisions_equal(caps[t].out, solo[t][1])
    # per-stream labeled series materialized
    assert metrics.by_label("segments") == {
        t: float(solo[t][0].segments) for t in bbs}


def test_fleet_victim_oom_isolated(tmp_path):
    bbs = {t: _make_bb(tmp_path, t, i)
           for i, t in enumerate(("s0", "s1", "s2"))}
    solo = {}
    for t, bb in bbs.items():
        metrics.reset()
        solo[t] = _solo(_mkcfg(tmp_path, t + "solo", bb))
    plan = "s1:dispatch:oom@1"
    metrics.reset()
    caps = {t: _Cap() for t in bbs}
    jp = {t: os.path.join(str(tmp_path), f"j_{t}.jsonl") for t in bbs}
    fleet = StreamFleet([
        StreamSpec(name=t,
                   cfg=_mkcfg(tmp_path, t, bb, fault_plan=plan,
                              telemetry_journal_path=jp[t]),
                   sinks=[caps[t]])
        for t, bb in bbs.items()])
    res = fleet.run()
    fleet.close()
    assert all(r.status == "done" for r in res.values())
    # victim demoted; demotion attributed to the victim only
    assert metrics.by_label("plan_demotions") == {"s1": 1.0}
    assert res["s1"].extras["plan"] != res["s0"].extras["plan"]
    # healthy streams bit-identical (time series included)
    for t in ("s0", "s2"):
        _decisions_equal(caps[t].out, solo[t][1])
    # victim: decisions exact (time series may carry the demoted
    # plan's documented tolerance)
    _decisions_equal(caps["s1"].out, solo["s1"][1], ts_exact=False)
    # v7 journals: stream-stamped; per-stream attribution fields
    for t in bbs:
        recs = [json.loads(line) for line in open(jp[t])]
        assert all(r["v"] == 11 and r["stream"] == t for r in recs)
        want = 1 if t == "s1" else 0
        assert recs[-1]["plan_demotions"] == want, t


def test_fleet_device_halt_shared_reinit(tmp_path):
    bbs = {t: _make_bb(tmp_path, t, i)
           for i, t in enumerate(("s0", "s1"))}
    solo = {}
    for t, bb in bbs.items():
        metrics.reset()
        solo[t] = _solo(_mkcfg(tmp_path, t + "solo", bb))
    metrics.reset()
    caps = {t: _Cap() for t in bbs}
    fleet = StreamFleet([
        StreamSpec(name=t,
                   cfg=_mkcfg(tmp_path, t, bb,
                              fault_plan="s1:dispatch:device_halt@2",
                              device_reinit_max=1),
                   sinks=[caps[t]])
        for t, bb in bbs.items()])
    res = fleet.run()
    fleet.close()
    assert all(r.status == "done" for r in res.values())
    # ONE shared reinit, attributed to the faulting stream
    assert metrics.get("device_reinits") == 1
    assert metrics.by_label("device_reinits") == {"s1": 1.0}
    for t in bbs:
        assert res[t].drained == solo[t][0].segments
        _decisions_equal(caps[t].out, solo[t][1], ts_exact=False)


def test_fleet_sink_wedge_sheds_victim_only(tmp_path):
    bb = _make_bb(tmp_path, "h", 1)
    metrics.reset()
    solo_stats, solo_out = _solo(_mkcfg(tmp_path, "hsolo", bb))

    class WedgeSink:
        def __init__(self):
            self.n = 0

        def push(self, work, positive):
            self.n += 1
            if self.n == 2:
                time.sleep(60)

    class SynthSource:
        """Real-time-ish source (no input file): hand-built
        segments, stream-adjacent seq stamps."""

        def __init__(self, data, n_seg):
            self.segs = [SegmentWork(data=data[i * N:(i + 1) * N],
                                     timestamp=i, seq=i)
                         for i in range(n_seg)]

        def __iter__(self):
            return iter(self.segs)

    raw = np.fromfile(bb, dtype=np.uint8)
    metrics.reset()
    hcap = _Cap()
    fleet = StreamFleet([
        StreamSpec(name="victim",
                   cfg=_mkcfg(tmp_path, "victim", "",
                              segment_deadline_s=0.2,
                              baseband_reserve_sample=False,
                              shutdown_join_timeout_s=0.5),
                   source=SynthSource(raw, SEGMENTS),
                   sinks=[WedgeSink()]),
        StreamSpec(name="h", cfg=_mkcfg(tmp_path, "h", bb),
                   sinks=[hcap]),
    ])
    t0 = time.time()
    res = fleet.run()
    elapsed = time.time() - t0
    assert elapsed < 30, f"fleet stalled behind the wedge ({elapsed})"
    # healthy stream untouched, bit-identical
    assert res["h"].status == "done" and res["h"].dropped == 0
    assert res["h"].drained == solo_stats.segments
    _decisions_equal(hcap.out, solo_out)
    # victim: accounted-only loss, attributed per stream
    v = res["victim"]
    assert v.dropped >= 1
    assert v.drained + v.dropped == SEGMENTS
    assert metrics.get("segments_dropped",
                       labels={"stream": "victim"}) == v.dropped
    assert metrics.get("segments_dropped",
                       labels={"stream": "h"}) == 0


def test_fleet_victim_manifest_rollback_isolated(tmp_path):
    from srtb_tpu.io.manifest import RunManifest
    bbs = {t: _make_bb(tmp_path, t, i)
           for i, t in enumerate(("v", "h"))}
    man = {t: os.path.join(str(tmp_path), f"man_{t}.jsonl")
           for t in bbs}

    def cfgs(tag_suffix=""):
        return {t: _mkcfg(tmp_path, t + tag_suffix, bb,
                          run_manifest_path=man[t])
                for t, bb in bbs.items()}

    # seed the victim's manifest namespace with crash debris: an
    # uncommitted intent + its orphaned artifact
    debris = os.path.join(str(tmp_path), "v_debris.npy")
    m = RunManifest.open(man["v"], fsync=False)
    m.intent((0, 0, "0:WriteSignalSink"), debris)
    m.sync()
    m.close()
    with open(debris, "wb") as f:
        f.write(b"orphan")
    metrics.reset()
    caps = {t: _Cap() for t in bbs}
    fleet = StreamFleet([
        StreamSpec(name=t, cfg=cfg, sinks=[caps[t]])
        for t, cfg in cfgs().items()])
    res = fleet.run()
    fleet.close()
    assert all(r.status == "done" for r in res.values())
    # the victim's debris was rolled back in ITS namespace only
    assert metrics.get("rolled_back_intents") == 1
    assert not os.path.exists(debris)
    assert os.path.exists(man["h"])


def test_fleet_admission_reject_and_queue(tmp_path):
    bb = _make_bb(tmp_path, "adm", 0)
    caps = {t: _Cap() for t in ("a", "b", "c")}

    def spec(t, prio):
        return StreamSpec(
            name=t,
            cfg=_mkcfg(tmp_path, t, bb, stream_priority=prio,
                       fleet_max_streams=1, fleet_queue_limit=1),
            sinks=[caps[t]])

    fleet = StreamFleet([spec("a", 0), spec("b", 5), spec("c", 9)])
    res = fleet.run()
    fleet.close()
    # capacity 1: a admitted; b queued then evicted by c (priority)
    assert res["a"].status == "done"
    assert res["c"].status == "done"
    assert res["b"].status == "rejected"
    assert not caps["b"].out
    # queued stream ran only after a slot freed; still one plan family
    assert fleet.plans.compiles == 1


def test_fleet_start_failure_frees_queued_slot(tmp_path):
    """A lane whose constructor fails must hand its capacity slot to
    the queued stream — a start failure with a populated waitlist
    used to leave run() spinning forever with no active lanes."""
    bb = _make_bb(tmp_path, "sf", 0)
    cap = _Cap()
    fleet = StreamFleet([
        # sanitize=True fails at lane start (fleet guardrail)
        StreamSpec(name="broken",
                   cfg=_mkcfg(tmp_path, "broken", bb, sanitize=True,
                              fleet_max_streams=1,
                              fleet_queue_limit=1)),
        StreamSpec(name="queued",
                   cfg=_mkcfg(tmp_path, "queued", bb),
                   sinks=[cap]),
    ])
    t0 = time.time()
    res = fleet.run()
    fleet.close()
    assert time.time() - t0 < 60
    assert res["broken"].status == "failed"
    assert res["queued"].status == "done" and cap.out


def test_fleet_healthz_per_stream(tmp_path):
    telemetry.register_stream("lane_a")
    telemetry.register_stream("lane_b")
    try:
        # startup: admitted streams with NO segment yet are healthy
        # (a lane inside its first cold compile must not 503 a
        # liveness probe), same contract as the solo engine's idle
        h = telemetry.health(stale_after_s=0.001)
        assert h["ok"]
        assert h["streams"]["lane_a"] == {"last_segment_age_s": None,
                                          "ok": True}
        telemetry.mark_segment("lane_a")
        telemetry.mark_segment("lane_b")
        h = telemetry.health(stale_after_s=30.0)
        assert h["ok"] and set(h["streams"]) == {"lane_a", "lane_b"}
        # age one stream past the deadline -> unhealthy with the
        # stale stream named, even though the OTHER stream (and the
        # global stamp) is fresh
        metrics.set(telemetry.LAST_SEGMENT_MONOTONIC,
                    time.monotonic() - 100,
                    labels={"stream": "lane_b"})
        telemetry.mark_segment("lane_a")
        h = telemetry.health(stale_after_s=30.0)
        assert not h["ok"] and h["stale_streams"] == ["lane_b"]
        assert h["streams"]["lane_a"]["ok"]
        # released streams stop counting
        telemetry.release_stream("lane_b")
        assert telemetry.health(stale_after_s=30.0)["ok"]
    finally:
        telemetry.release_stream("lane_a")
        telemetry.release_stream("lane_b")


def test_fleet_prometheus_labels(tmp_path):
    bb = _make_bb(tmp_path, "prom", 0)
    fleet = StreamFleet([
        StreamSpec(name="beam0", cfg=_mkcfg(tmp_path, "beam0", bb),
                   sinks=[_Cap()])])
    fleet.run()
    fleet.close()
    prom = metrics.prometheus()
    assert 'srtb_inflight_depth{stream="beam0"}' in prom
    assert 'srtb_segments{stream="beam0"}' in prom


# ------------------------------------------------- v7 schema + report


def test_span_schema_v7_stream_field():
    from srtb_tpu.utils.telemetry import (SPAN_SCHEMA_VERSION,
                                          segment_span)
    assert SPAN_SCHEMA_VERSION == 11
    rec = segment_span(0, {"ingest": 0.01}, 1, 0, False, 4)
    assert rec["v"] == 11 and "stream" not in rec
    metrics.set("plan_demotions", 7)  # global; must NOT leak into a
    metrics.add("plan_demotions", 2, labels={"stream": "x"})
    rec = segment_span(0, {"ingest": 0.01}, 1, 0, False, 4,
                       stream="x")
    assert rec["stream"] == "x"
    # named spans carry the stream's OWN attribution counters
    assert rec["plan_demotions"] == 2


def test_report_mixed_v5_v6(tmp_path):
    from srtb_tpu.tools import telemetry_report as TR
    path = os.path.join(str(tmp_path), "mixed.jsonl")
    v5 = {"type": "segment_span", "v": 5, "ts": 1.0, "segment": 0,
          "stages_ms": {"ingest": 1.0}, "queue_depth": 1,
          "detections": 2, "dump": True, "samples": 100,
          "segments_dropped": 0, "degrade_level": 0,
          "plan_demotions": 0}
    v6a = dict(v5, v=6, ts=2.0, segment=1, stream="s0",
               plan_demotions=1, segments_dropped=2)
    v6b = dict(v5, v=6, ts=3.0, segment=1, stream="s1")
    with open(path, "w") as f:
        for r in (v5, v6a, v6b):
            f.write(json.dumps(r) + "\n")
    rep = TR.report(path)
    assert rep["records"] == 3
    fl = rep["fleet"]
    # v5 record (no stream) drops out of the fleet section
    assert set(fl) == {"s0", "s1"}
    assert fl["s0"]["plan_demotions"] == 1
    assert fl["s0"]["segments_dropped"] == 2
    assert fl["s1"]["plan_demotions"] == 0
    md = TR._md(rep)
    assert "Fleet (per-stream)" in md and "| s0 |" in md
    # a journal with no v6 spans has no fleet section
    solo_path = os.path.join(str(tmp_path), "solo.jsonl")
    with open(solo_path, "w") as f:
        f.write(json.dumps(v5) + "\n")
    rep = TR.report(solo_path)
    assert rep["fleet"] == {}
    assert "Fleet" not in TR._md(rep)


# --------------------------------------------------------- guardrails


def test_fleet_rejects_sanitize_and_micro_batch(tmp_path):
    bb = _make_bb(tmp_path, "g", 0)
    fleet = StreamFleet([
        StreamSpec(name="s", cfg=_mkcfg(tmp_path, "s", bb,
                                        sanitize=True),
                   sinks=[_Cap()])])
    res = fleet.run()
    assert res["s"].status == "failed"
    assert isinstance(res["s"].error, ValueError)
    # REAL-TIME lanes (no input file) still reject micro-batch
    # loudly: batching a live stream trades bounded latency for
    # throughput silently
    rt_cfg = _mkcfg(tmp_path, "s", bb, micro_batch_segments=2,
                    inflight_segments=2).replace(input_file_path="")
    fleet = StreamFleet([
        StreamSpec(name="s", cfg=rt_cfg, source=iter(()),
                   sinks=[_Cap()])])
    res = fleet.run()
    assert res["s"].status == "failed"
    assert isinstance(res["s"].error, ValueError)
    assert "file-mode" in str(res["s"].error)
    # FILE-mode lanes accept it (the archive-replay shape): B
    # segments per vmapped dispatch inside the fleet
    cap = _Cap()
    fleet = StreamFleet([
        StreamSpec(name="s", cfg=_mkcfg(tmp_path, "smb", bb,
                                        micro_batch_segments=2,
                                        inflight_segments=4),
                   sinks=[cap])])
    res = fleet.run()
    assert res["s"].status == "done"
    assert res["s"].drained == len(cap.out) > 0
    # a batch bigger than the lane window still rejects
    fleet = StreamFleet([
        StreamSpec(name="s", cfg=_mkcfg(tmp_path, "sbig", bb,
                                        micro_batch_segments=4,
                                        inflight_segments=2),
                   sinks=[_Cap()])])
    res = fleet.run()
    assert res["s"].status == "failed"
    assert "exceeds" in str(res["s"].error)


def test_fleet_duplicate_names_rejected(tmp_path):
    bb = _make_bb(tmp_path, "d", 0)
    with pytest.raises(ValueError, match="duplicate"):
        StreamFleet([
            StreamSpec(name="s", cfg=_mkcfg(tmp_path, "s1", bb)),
            StreamSpec(name="s", cfg=_mkcfg(tmp_path, "s2", bb))])


def test_fleet_lane_failure_contained(tmp_path):
    """A FATAL fault in one lane fails that lane only; neighbors
    finish and the failed lane's loss is accounted per stream."""
    bbs = {t: _make_bb(tmp_path, t, i)
           for i, t in enumerate(("bad", "good"))}
    metrics.reset()
    solo_stats, solo_out = _solo(_mkcfg(tmp_path, "gsolo",
                                        bbs["good"]))
    metrics.reset()
    gcap = _Cap()
    fleet = StreamFleet([
        StreamSpec(name="bad",
                   cfg=_mkcfg(tmp_path, "bad", bbs["bad"],
                              fault_plan="bad:dispatch:fatal@1"),
                   sinks=[_Cap()]),
        StreamSpec(name="good",
                   cfg=_mkcfg(tmp_path, "good", bbs["good"]),
                   sinks=[gcap]),
    ])
    res = fleet.run()
    fleet.close()
    assert res["bad"].status == "failed"
    assert res["good"].status == "done"
    _decisions_equal(gcap.out, solo_out)
    # nothing vanished from the failed lane's books: everything it
    # dispatched but never drained is accounted loss
    bad = res["bad"]
    assert bad.drained + bad.dropped == bad.stats.segments


# ------------------------------------- elastic pool: drain + migration


def test_fleet_pool_scoped_halt_drains_victim_only(tmp_path):
    """Satellite 1: with >= 2 pool members, a device HALT is no longer
    the shared domain — the faulted member is drained (its plan cache
    alone force-retired, its lanes live-migrated onto the survivor)
    and the budgeted fleet-wide reinit is NOT spent.  The migrant
    rejoins the survivor's plan family at rung 0: pool-wide compiles
    stay at one per device and every stream stays bit-identical."""
    bbs = {t: _make_bb(tmp_path, t, i)
           for i, t in enumerate(("s0", "s1"))}
    solo = {}
    for t, bb in bbs.items():
        metrics.reset()
        solo[t] = _solo(_mkcfg(tmp_path, t + "solo", bb))
    metrics.reset()
    caps = {t: _Cap() for t in bbs}
    fleet = StreamFleet([
        StreamSpec(name=t,
                   cfg=_mkcfg(tmp_path, t, bb,
                              fleet_devices=2,
                              fault_plan="s1:dispatch:device_halt@2",
                              device_reinit_max=1),
                   sinks=[caps[t]])
        for t, bb in bbs.items()])
    assert len(fleet.pool) == 2
    res = fleet.run()
    pool_compiles = fleet.pool.compiles
    halted = fleet.pool.devices[1].state
    fleet.close()
    assert all(r.status == "done" for r in res.values())
    # the reinit budget was available and must NOT have been spent
    assert metrics.get("device_reinits") == 0
    assert metrics.get("device_drains") == 1
    assert metrics.by_label("migrations") == {"s1": 1.0}
    from srtb_tpu.pipeline.pool import STATE_HALTED
    assert halted == STATE_HALTED  # a member halts at most once
    # deterministic placement: s0 -> dev0, s1 -> dev1; the victim
    # drained onto the survivor
    assert res["s0"].extras["device"] == "dev0"
    assert res["s1"].extras["device"] == "dev0"
    assert res["s1"].extras["migrations"] == 1
    # one compile per device, zero recompiles for the migration (the
    # migrant adopted the survivor's family at rung 0)
    assert pool_compiles == 2
    assert metrics.get("plan_demotions") == 0
    for t in bbs:
        assert res[t].dropped == 0
        assert res[t].drained == solo[t][0].segments
        _decisions_equal(caps[t].out, solo[t][1])


def test_batch_former_membership_revalidated(tmp_path):
    """Satellite 2: a migrated/healed lane can never batch into its
    FORMER device's family — eligibility keys on the lane's CURRENT
    processor identity and its member's health state."""
    from types import SimpleNamespace

    from srtb_tpu.pipeline.fleet import _BatchFormer
    from srtb_tpu.pipeline.pool import (STATE_DRAINING, STATE_OK,
                                        DevicePool)

    pool = DevicePool(2)

    class _Proc:
        _fleet_shared = True
        staged = False

    class _Lane:
        def __init__(self, proc, dev):
            self.pipe = SimpleNamespace(processor=proc)
            self.device = dev

        def _unit(self):
            return 1

    former = _BatchFormer(SimpleNamespace(_tsan=None),
                          batch_max=3, linger_s=1.0)
    proc_a, proc_b = _Proc(), _Proc()
    lane0 = _Lane(proc_a, pool.devices[0])
    lane1 = _Lane(proc_a, pool.devices[0])
    lane2 = _Lane(proc_b, pool.devices[1])
    assert all(former.eligible(ln) for ln in (lane0, lane1, lane2))
    # a draining/halted member's lanes stop offering immediately
    pool.devices[0].set_state(STATE_DRAINING)
    assert not former.eligible(lane0) and not former.eligible(lane1)
    assert former.eligible(lane2)
    pool.devices[0].set_state(STATE_OK)
    # groups key on processor identity: per-device families can never
    # merge, and a migration (which swaps in the TARGET cache's
    # processor) moves the lane to the target's group by construction
    former.offer(lane0, (object(), 0.0, 0), 0)
    former.offer(lane2, (object(), 0.0, 0), 0)
    assert len(former._groups) == 2
    assert {id(proc_a), id(proc_b)} == set(former._groups)
    # after a simulated migration lane1 carries dev1's processor: its
    # next offer joins dev1's family, not dev0's
    lane1.pipe.processor = proc_b
    lane1.device = pool.devices[1]
    former.offer(lane1, (object(), 0.0, 1), 1)
    assert len(former._groups[id(proc_b)][1]) == 2
    assert len(former._groups[id(proc_a)][1]) == 1


def test_fleet_stream_killed_on_a_resumes_on_b(tmp_path):
    """Satellite 3: a stream killed mid-segment on device A resumes
    on device B (pin_device) — final output set bit-identical to an
    uninterrupted solo run, manifest fsck-clean."""
    from srtb_tpu.tools.crash_soak import snapshot_outputs
    from srtb_tpu.tools.fsck import fsck

    bb = _make_bb(tmp_path, "mig", 5)

    def _dcfg(tag, run_dir, **kw):
        # default sinks (the artifact writers), deterministic names,
        # detection relaxed so segments actually commit artifacts
        run_dir.mkdir(exist_ok=True)
        return _mkcfg(
            tmp_path, tag, bb,
            baseband_output_file_prefix=str(run_dir / "out_"),
            checkpoint_path=str(run_dir / "ck.json"),
            run_manifest_path=str(run_dir / "manifest.jsonl"),
            deterministic_timestamps=True,
            mitigate_rfi_average_method_threshold=1000.0,
            mitigate_rfi_spectral_kurtosis_threshold=50.0,
            signal_detect_signal_noise_threshold=2.0,
            signal_detect_max_boxcar_length=8,
            inflight_segments=1, **kw)

    golden_dir = tmp_path / "golden_run"
    metrics.reset()
    with Pipeline(_dcfg("golden", golden_dir)) as pipe:
        pipe.run()
    golden = snapshot_outputs(str(golden_dir))
    assert golden  # the equality gate below must gate something

    # phase 1: the stream dies on dev0 after a segment committed but
    # before its checkpoint landed (THE duplicate window)
    run_dir = tmp_path / "mig_run"
    metrics.reset()
    fleet = StreamFleet([StreamSpec(
        name="mig",
        cfg=_dcfg("p1", run_dir, fleet_devices=2,
                  fault_plan="checkpoint:fatal@1"),
        pin_device=0)])
    res1 = fleet.run()
    fleet.close()
    assert res1["mig"].status == "failed"
    assert res1["mig"].extras["device"] == "dev0"

    # phase 2: resume the SAME run pinned to dev1
    metrics.reset()
    fleet = StreamFleet([StreamSpec(
        name="mig", cfg=_dcfg("p2", run_dir, fleet_devices=2),
        pin_device=1)])
    res2 = fleet.run()
    fleet.close()
    assert res2["mig"].status == "done"
    assert res2["mig"].extras["device"] == "dev1"
    assert res2["mig"].dropped == 0
    # exactly-once across the device move: the union of both phases'
    # outputs equals the uninterrupted golden, byte for byte
    assert snapshot_outputs(str(run_dir)) == golden
    rep = fsck(str(run_dir / "manifest.jsonl"),
               str(run_dir / "ck.json"))
    assert rep["clean"], rep


def test_fleet_rebalance_on_slo_burn(tmp_path, monkeypatch):
    """Driver (b): a burning stream on the loaded member migrates to
    the strictly less-loaded peer (migrate_on_burn), exactly once
    (cooldown), with decisions bit-identical to solo."""
    from srtb_tpu.utils import slo

    class _Burning:
        def evaluate(self):
            # s2 sits on dev0 (load 2) next to s0; dev1 holds s1 only
            return {"s2": {"ok": False}}

        def note_segment(self, *a, **k):
            pass

        note_dropped = note_canary = note_segment

    monkeypatch.setattr(slo, "tracker", _Burning())
    bbs = {t: _make_bb(tmp_path, t, i)
           for i, t in enumerate(("s0", "s1", "s2"))}
    metrics.reset()
    solo = _solo(_mkcfg(tmp_path, "s2solo", bbs["s2"]))
    metrics.reset()
    caps = {t: _Cap() for t in bbs}
    fleet = StreamFleet([
        StreamSpec(name=t,
                   cfg=_mkcfg(tmp_path, t, bb, fleet_devices=2,
                              migrate_on_burn=True),
                   sinks=[caps[t]])
        for t, bb in bbs.items()])
    res = fleet.run()
    fleet.close()
    assert all(r.status == "done" for r in res.values())
    assert res["s2"].extras["device"] == "dev1"
    assert res["s2"].extras["migrations"] == 1
    assert metrics.by_label("migrations") == {"s2": 1.0}
    # the rebalance is a drain-migrate, not a fault: nothing reinits,
    # nothing demotes, nothing drops
    assert metrics.get("device_reinits") == 0
    assert metrics.get("plan_demotions") == 0
    assert res["s2"].dropped == 0
    _decisions_equal(caps["s2"].out, solo[1])


def test_fleet_rolling_restart_drains_one_at_a_time(tmp_path):
    """Driver (c): an operator rolling restart drains every member
    exactly once, lanes live-migrate onto peers and every stream
    finishes bit-identical with zero loss."""
    bbs = {t: _make_bb(tmp_path, t, i)
           for i, t in enumerate(("s0", "s1"))}
    solo = {}
    for t, bb in bbs.items():
        metrics.reset()
        solo[t] = _solo(_mkcfg(tmp_path, t + "solo", bb))
    metrics.reset()
    caps = {t: _Cap() for t in bbs}
    fleet = StreamFleet([
        StreamSpec(name=t,
                   cfg=_mkcfg(tmp_path, t, bb, fleet_devices=2),
                   sinks=[caps[t]])
        for t, bb in bbs.items()])
    fleet.rolling_restart()
    res = fleet.run()
    pool_states = [d.state for d in fleet.pool.devices]
    fleet.close()
    assert all(r.status == "done" for r in res.values())
    assert metrics.get("device_drains") == 2
    assert metrics.get("migrations") >= 2
    assert metrics.get("device_reinits") == 0
    from srtb_tpu.pipeline.pool import STATE_OK
    assert pool_states == [STATE_OK, STATE_OK]  # drained members re-arm
    for t in bbs:
        assert res[t].dropped == 0
        assert res[t].drained == solo[t][0].segments
        _decisions_equal(caps[t].out, solo[t][1])


# ----------------------------------------------------- fleet soak gate


@pytest.mark.slow
def test_fleet_soak_gate():
    from srtb_tpu.tools.fleet_soak import run_soak
    report = run_soak(streams=3, segments=4, log2n=12)
    assert report["ok"]
    assert report["plan_compiles"] == 1
    assert report["plan_cache_hits"] == 2


@pytest.mark.slow
def test_fleet_soak_selftest_sharp():
    from srtb_tpu.tools.fleet_soak import selftest
    assert selftest(log2n=11) == []


@pytest.mark.slow
def test_fleet_migrate_soak_gate():
    from srtb_tpu.tools.fleet_soak import run_migrate
    report = run_migrate(streams=3, segments=6, log2n=12)
    assert report["ok"]
    assert report["device_drains"] == 1
    assert report["migrations"] >= 1
