"""Async in-flight segment engine tests (pipeline/runtime.py).

Covers the acceptance criteria of the overlap engine:
- determinism: the overlapped engine produces bit-identical detect
  outputs and identical journal segment ordering vs the serial path;
- the CPU A/B harness (slow source + sleep-stub device + slow sink)
  shows the overlapped engine beating the serial path by >= 25%
  segments/s while journaling overlap_hidden_ms > 0;
- backpressure with a full in-flight window surfaces as *accounted*
  loss (segments_dropped) with a clean exit, never a stall;
- micro-batch mode (B segments in one vmapped jit call) matches the
  single-segment plan's detections;
- /metrics exposes the srtb_inflight_depth gauge;
- the telemetry report tolerates mixed v1/v2 journals.
"""

import json
import time
from typing import NamedTuple

import numpy as np
import pytest

from srtb_tpu.config import Config
from srtb_tpu.io.backpressure import DropOldestSegmentBuffer
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.pipeline.runtime import Pipeline
from srtb_tpu.pipeline.work import SegmentWork
from srtb_tpu.utils.metrics import metrics


# ------------------------------------------------------------- fixtures


@pytest.fixture(scope="module")
def synth_file(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("overlap")
    n = 1 << 16
    data = make_dispersed_baseband(n * 4, 1405.0, 64.0, 1.0,
                                   pulse_positions=n // 2, nbits=8)
    path = str(tmp / "bb.bin")
    data.tofile(path)
    return path, n


def _cfg(path, n, tmp_path, tag, **extra):
    return Config(
        baseband_input_count=n,
        baseband_input_bits=8,
        baseband_freq_low=1405.0,
        baseband_bandwidth=64.0,
        baseband_sample_rate=128e6,
        dm=1.0,
        input_file_path=path,
        baseband_output_file_prefix=str(tmp_path / f"{tag}_"),
        spectrum_channel_count=1 << 8,
        signal_detect_max_boxcar_length=64,
        mitigate_rfi_average_method_threshold=100.0,
        mitigate_rfi_spectral_kurtosis_threshold=2.0,
        baseband_reserve_sample=False,
        writer_thread_count=0,
        telemetry_journal_path=str(tmp_path / f"{tag}.jsonl"),
        **extra)


class _CaptureSink:
    """Records each drained segment's detect outputs as host arrays."""

    def __init__(self):
        self.detects = []
        self.positives = []

    def push(self, work, positive):
        det = work.detect
        self.detects.append((
            np.asarray(det.signal_counts).copy(),
            np.asarray(det.zero_count).copy(),
            np.asarray(det.time_series).copy()))
        self.positives.append(bool(positive))


def _run(cfg, sink=None):
    sinks = [sink] if sink is not None else []
    with Pipeline(cfg, sinks=sinks) as pipe:
        stats = pipe.run()
    return stats


# ----------------------------------------------------- determinism A/B


def test_overlapped_engine_bit_identical_to_serial(synth_file, tmp_path):
    """Same jit program either way: the in-flight window must change
    scheduling only, never results or journal ordering."""
    from srtb_tpu.tools import telemetry_report as TR

    path, n = synth_file
    out = {}
    for tag, w in (("serial", 1), ("overlap", 3)):
        metrics.reset()
        sink = _CaptureSink()
        cfg = _cfg(path, n, tmp_path, tag, inflight_segments=w)
        stats = _run(cfg, sink)
        recs = TR.load(cfg.telemetry_journal_path)
        out[tag] = (stats, sink, recs)
    s_stats, s_sink, s_recs = out["serial"]
    o_stats, o_sink, o_recs = out["overlap"]
    assert s_stats.segments == o_stats.segments == 4
    assert s_stats.signals == o_stats.signals >= 1
    assert len(s_sink.detects) == len(o_sink.detects) == 4
    for (sc_a, zc_a, ts_a), (sc_b, zc_b, ts_b) in zip(
            s_sink.detects, o_sink.detects):
        np.testing.assert_array_equal(sc_a, sc_b)
        np.testing.assert_array_equal(zc_a, zc_b)
        np.testing.assert_array_equal(ts_a, ts_b)
    assert s_sink.positives == o_sink.positives
    # journal ordering identical and monotonic in both modes
    assert [r["segment"] for r in s_recs] == list(range(4))
    assert [r["segment"] for r in o_recs] == list(range(4))
    # v2+v3+v4 schema fields present (v4 adds the compute-health
    # counters)
    for r in o_recs:
        assert r["v"] == 11
        assert "overlap_hidden_ms" in r
        assert r["inflight_depth"] >= 1
        assert r["degrade_level"] == 0 and r["retries"] == 0
    metrics.reset()


def test_micro_batch_matches_single_segment(synth_file, tmp_path):
    """B segments stacked into one vmapped jit call must yield the same
    detections as the single-segment plan (different XLA program, so
    counts exact + time series allclose, not bitwise)."""
    path, n = synth_file
    metrics.reset()
    sink_1 = _CaptureSink()
    _run(_cfg(path, n, tmp_path, "mb1", inflight_segments=1), sink_1)
    sink_b = _CaptureSink()
    cfg_b = _cfg(path, n, tmp_path, "mb2", inflight_segments=4,
                 micro_batch_segments=2)
    stats_b = _run(cfg_b, sink_b)
    assert stats_b.segments == 4
    assert len(sink_b.detects) == len(sink_1.detects) == 4
    for (sc_a, zc_a, ts_a), (sc_b, zc_b, ts_b) in zip(
            sink_1.detects, sink_b.detects):
        np.testing.assert_array_equal(sc_a, sc_b)
        np.testing.assert_array_equal(zc_a, zc_b)
        np.testing.assert_allclose(ts_a, ts_b, rtol=1e-5,
                                   atol=1e-4 * np.abs(ts_a).max())
    assert sink_1.positives == sink_b.positives
    # batch dispatches are admission-gated on the whole unit fitting:
    # in-flight depth never exceeds the configured window
    from srtb_tpu.tools import telemetry_report as TR
    depths = [r["inflight_depth"]
              for r in TR.load(cfg_b.telemetry_journal_path)]
    assert depths and max(depths) <= cfg_b.inflight_segments
    metrics.reset()


def test_micro_batch_validation():
    """Config errors must be loud: a batch larger than the window, and
    micro-batching the staged plan, both raise."""
    from srtb_tpu.pipeline.segment import SegmentProcessor

    cfg = Config(baseband_input_count=1 << 12,
                 baseband_reserve_sample=False,
                 inflight_segments=2, micro_batch_segments=4)
    proc = SegmentProcessor(cfg)

    class _NoSource:
        def __iter__(self):
            return iter(())

    pipe = Pipeline(cfg, source=_NoSource(), sinks=[], processor=proc)
    with pytest.raises(ValueError, match="exceeds"):
        pipe.run()
    staged = SegmentProcessor(cfg, staged=True)
    with pytest.raises(ValueError, match="fused plan"):
        staged.process_batch(np.zeros((2, 1 << 12), np.uint8))
    # run() rejects the staged+micro-batch combination up front, before
    # any segment is ingested or stacked
    cfg_ok = cfg.replace(inflight_segments=4)
    staged_pipe = Pipeline(cfg_ok, source=_NoSource(), sinks=[],
                           processor=staged)
    with pytest.raises(ValueError, match="fused plan"):
        staged_pipe.run()
    with pytest.raises(ValueError, match="batch must be"):
        proc.process_batch(np.zeros((2, 7), np.uint8))


def test_micro_batch_checkpoint_offsets_are_per_segment(synth_file,
                                                        tmp_path):
    """Each drained segment must checkpoint the source offset after ITS
    OWN ingest, not the post-batch offset: a crash after a partially
    drained batch must resume at the first undrained segment."""
    path, n = synth_file
    cfg = _cfg(path, n, tmp_path, "ckpt", inflight_segments=4,
               micro_batch_segments=2,
               checkpoint_path=str(tmp_path / "ckpt.json"))
    pipe = Pipeline(cfg, sinks=[])
    updates = []
    orig = pipe.checkpoint.update
    pipe.checkpoint.update = lambda done, off: (
        updates.append((done, off)), orig(done, off))
    with pipe:
        stats = pipe.run(max_segments=3)  # one full batch + a tail
    assert stats.segments == 3
    seg_bytes = cfg.segment_bytes(1)
    # reserve_sample=False: offsets advance one whole segment per drain
    assert updates == [(1, seg_bytes), (2, 2 * seg_bytes),
                       (3, 3 * seg_bytes)]


# ------------------------------------------------- sleep-stub A/B rig


class _StubDetect(NamedTuple):
    signal_counts: object
    zero_count: object
    time_series: object


class _AsyncStub:
    """Async device-array stand-in: ready at ``t_done``; a host fetch
    blocks until then (like a blocking device sync)."""

    def __init__(self, value, t_done):
        self._value = np.asarray(value)
        self._t_done = t_done

    def is_ready(self) -> bool:
        return time.perf_counter() >= self._t_done

    def __array__(self, dtype=None, copy=None):
        while time.perf_counter() < self._t_done:
            time.sleep(0.001)
        return self._value


class _SleepStubProcessor:
    """Device stub: dispatch returns immediately, results materialize
    ``device_s`` later; the device executes segments serially (segment
    k+1 starts only when k finishes), like a real accelerator queue."""

    def __init__(self, device_s: float):
        self.device_s = device_s
        self._free_at = 0.0

    def process(self, raw):
        t_done = max(time.perf_counter(), self._free_at) + self.device_s
        self._free_at = t_done
        det = _StubDetect(
            signal_counts=_AsyncStub(np.zeros((1, 4), np.int64), t_done),
            zero_count=_AsyncStub(np.asarray(0), t_done),
            time_series=_AsyncStub(np.zeros(8, np.float32), t_done))
        return None, det


class _SlowSource:
    """N segments, each costing ``ingest_s`` of host time to produce."""

    def __init__(self, n_segments: int, ingest_s: float,
                 seg_bytes: int = 64):
        self.n = n_segments
        self.ingest_s = ingest_s
        self.seg_bytes = seg_bytes
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self) -> SegmentWork:
        if self._i >= self.n:
            raise StopIteration
        time.sleep(self.ingest_s)
        self._i += 1
        return SegmentWork(data=np.zeros(self.seg_bytes, np.uint8),
                           timestamp=self._i)


class _SlowSink:
    def __init__(self, sink_s: float):
        self.sink_s = sink_s
        self.count = 0

    def push(self, work, positive):
        time.sleep(self.sink_s)
        self.count += 1


def _stub_pipeline(tmp_path, tag, n_seg, window, ingest_s, device_s,
                   sink_s):
    cfg = Config(baseband_input_count=64,
                 baseband_reserve_sample=False,
                 inflight_segments=window, writer_thread_count=0,
                 telemetry_journal_path=str(tmp_path / f"{tag}.jsonl"))
    sink = _SlowSink(sink_s)
    pipe = Pipeline(cfg, source=_SlowSource(n_seg, ingest_s), sinks=[sink],
                    processor=_SleepStubProcessor(device_s))
    stats = pipe.run()
    pipe.close()
    return cfg, stats, sink


def test_overlap_ab_harness_hides_host_time(tmp_path):
    """The acceptance A/B: slow source + sleep-stub device + slow sink.
    Serial pays ingest + device + sink per segment; the overlapped
    engine hides ingest and sink under device compute, so segments/s
    must improve by >= 25% (the modeled win here is ~2x) and the
    journal must show overlap_hidden_ms > 0."""
    from srtb_tpu.tools import telemetry_report as TR

    metrics.reset()
    n_seg, ingest_s, device_s, sink_s = 10, 0.02, 0.04, 0.02
    _, s_stats, s_sink = _stub_pipeline(
        tmp_path, "ab_serial", n_seg, 1, ingest_s, device_s, sink_s)
    cfg_o, o_stats, o_sink = _stub_pipeline(
        tmp_path, "ab_overlap", n_seg, 3, ingest_s, device_s, sink_s)
    assert s_stats.segments == o_stats.segments == n_seg
    assert s_sink.count == o_sink.count == n_seg
    serial_rate = n_seg / s_stats.elapsed_s
    overlap_rate = n_seg / o_stats.elapsed_s
    assert overlap_rate >= 1.25 * serial_rate, (
        f"overlap {overlap_rate:.2f} seg/s vs serial "
        f"{serial_rate:.2f} seg/s")
    recs = TR.load(cfg_o.telemetry_journal_path)
    assert len(recs) == n_seg
    assert [r["segment"] for r in recs] == list(range(n_seg))
    # most segments' host work hid under device compute
    hidden = [r["overlap_hidden_ms"] for r in recs]
    assert sum(1 for h in hidden if h > 0) >= n_seg - 2
    rep = TR.report(cfg_o.telemetry_journal_path)
    assert rep["overlap"]["efficiency"] > 0.3
    assert rep["stages"]["overlap"]["count"] == n_seg
    # the inflight gauge is exposed to Prometheus
    assert "srtb_inflight_depth" in metrics.prometheus()
    metrics.reset()


# ------------------------------------------------ backpressure / loss


def test_full_window_backpressure_is_accounted_loss(tmp_path):
    """A source faster than the device with a full in-flight window:
    the excess must surface as accounted segments_dropped (drop-oldest
    buffer), the engine must keep draining, and the run must exit
    cleanly with ordered journal records — never stall."""
    from srtb_tpu.tools import telemetry_report as TR

    metrics.reset()
    n_seg = 24
    src = DropOldestSegmentBuffer(_SlowSource(n_seg, 0.001), capacity=3)
    cfg = Config(baseband_input_count=64,
                 baseband_reserve_sample=False,
                 inflight_segments=2, writer_thread_count=0,
                 telemetry_journal_path=str(tmp_path / "bp.jsonl"))
    pipe = Pipeline(cfg, source=src, sinks=[],
                    processor=_SleepStubProcessor(0.02))
    stats = pipe.run()
    pipe.close()
    src.close()
    dropped = metrics.get("segments_dropped")
    assert dropped > 0, "overload must surface as accounted loss"
    assert src.dropped == dropped
    # nothing lost silently: every produced segment was either drained
    # or accounted as dropped
    assert stats.segments + src.dropped == n_seg
    recs = TR.load(cfg.telemetry_journal_path)
    assert len(recs) == stats.segments
    segs = [r["segment"] for r in recs]
    assert segs == sorted(segs)
    # the journal's cumulative drop counter caught the loss
    assert recs[-1]["segments_dropped"] == dropped
    metrics.reset()


def test_drop_oldest_buffer_clean_passthrough():
    """No overload -> no drops, all segments delivered in order."""
    metrics.reset()
    src = DropOldestSegmentBuffer(_SlowSource(5, 0.0), capacity=8)
    got = [seg.timestamp for seg in src]
    assert got == [1, 2, 3, 4, 5]
    assert src.dropped == 0
    src.close()
    metrics.reset()


def test_drop_oldest_buffer_propagates_source_error():
    class _Boom:
        def __iter__(self):
            return self

        def __next__(self):
            raise OSError("receiver died")

    src = DropOldestSegmentBuffer(_Boom(), capacity=2)
    with pytest.raises(OSError, match="receiver died"):
        next(iter(src))
    src.close()


def test_sink_failure_propagates_from_pipe(tmp_path):
    """A crashing sink on the off-critical-path pipe must fail the run
    loudly, not hang the engine or lose the exception."""

    class _BoomSink:
        def push(self, work, positive):
            raise RuntimeError("sink exploded")

    metrics.reset()
    cfg = Config(baseband_input_count=64, baseband_reserve_sample=False,
                 inflight_segments=3, writer_thread_count=0)
    pipe = Pipeline(cfg, source=_SlowSource(6, 0.0), sinks=[_BoomSink()],
                    processor=_SleepStubProcessor(0.001))
    with pytest.raises(RuntimeError, match="sink exploded"):
        pipe.run()
    pipe.close()
    metrics.reset()


# ------------------------------------------------ mixed-schema journal


def test_telemetry_report_tolerates_mixed_v1_v2(tmp_path):
    """Rotation can leave a v1 tail next to v2 records: the report must
    summarize both without KeyError, and overlap stats must cover only
    the records that carry the v2 fields."""
    from srtb_tpu.tools import telemetry_report as TR

    path = tmp_path / "mixed.jsonl"
    with open(path, "w") as f:
        # v1 record: no overlap_hidden_ms / inflight_depth / samples
        f.write(json.dumps({
            "type": "segment_span", "v": 1, "ts": 1000.0, "segment": 0,
            "stages_ms": {"dispatch": 2.0, "fetch": 1.0},
            "queue_depth": 1, "detections": 0, "dump": False}) + "\n")
        # degenerate v1 record: no stages_ms at all
        f.write(json.dumps({
            "type": "segment_span", "v": 1, "ts": 1000.5,
            "segment": 1}) + "\n")
        # v2 record
        f.write(json.dumps({
            "type": "segment_span", "v": 2, "ts": 1001.0, "segment": 2,
            "stages_ms": {"dispatch": 2.0, "fetch": 1.0, "sink": 1.0},
            "queue_depth": 2, "detections": 1, "dump": True,
            "samples": 64, "overlap_hidden_ms": 3.0,
            "inflight_depth": 2}) + "\n")
    rep = TR.report(str(path))
    assert rep["records"] == 3
    assert rep["stages"]["dispatch"]["count"] == 2
    # overlap section: only the v2 record qualifies
    ov = rep["overlap"]
    assert ov["records"] == 1
    assert ov["hidden_mean_ms"] == 3.0
    assert ov["efficiency"] == 0.75  # 3 hidden vs 1 blocked fetch
    assert ov["inflight_depth_max"] == 2
    # overlap pseudo-stage present but excluded from the segment sum
    assert rep["stages"]["overlap"]["count"] == 1
    assert rep["stages"]["segment"]["max_ms"] == 4.0
    md = TR._md(rep)
    assert "Overlap (async engine)" in md
    assert TR.main([str(path), "--format", "json"]) == 0


def test_timeline_stall_shows_zero_bins(tmp_path):
    """A mid-run stall (no journal records for a stretch) must render
    as explicit 0-seg/s bins, not silently missing rows."""
    from srtb_tpu.tools import telemetry_report as TR

    path = tmp_path / "stall.jsonl"
    with open(path, "w") as f:
        for ts in (1000.0, 1001.0, 1035.0):  # 30+ s gap mid-run
            f.write(json.dumps({"type": "segment_span", "v": 2,
                                "ts": ts, "segment": 0,
                                "stages_ms": {"sink": 1.0},
                                "samples": 1}) + "\n")
    tl = TR.timeline(TR.load(str(path)), bin_s=10.0)
    assert [b["t_start_s"] for b in tl] == [0.0, 10.0, 20.0, 30.0]
    assert tl[1]["segments"] == 0 and tl[1]["segments_per_sec"] == 0.0
    assert tl[2]["segments"] == 0
    assert tl[0]["segments"] == 2 and tl[3]["segments"] == 1
