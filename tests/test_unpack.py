"""Unpack tests.

Oracle style follows the reference's test-unpack.cpp: hand-computed bit
patterns for sub-byte widths (test-unpack.cpp:63-139) plus random-data
self-consistency against an independent numpy model (test-unpack.cpp:236-253).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from srtb_tpu.ops import unpack as U


def test_unpack_1bit_pattern():
    # 0b10110001 -> 1,0,1,1,0,0,0,1 (MSB first, ref: unpack.hpp:91-98)
    data = jnp.asarray(np.array([0b10110001], dtype=np.uint8))
    out = np.asarray(U.unpack(data, 1))
    np.testing.assert_array_equal(out, [1, 0, 1, 1, 0, 0, 0, 1])


def test_unpack_2bit_pattern():
    # 0b10110001 -> 0b10, 0b11, 0b00, 0b01 (ref: unpack.hpp:116-119)
    data = jnp.asarray(np.array([0b10110001], dtype=np.uint8))
    out = np.asarray(U.unpack(data, 2))
    np.testing.assert_array_equal(out, [2, 3, 0, 1])


def test_unpack_4bit_pattern():
    data = jnp.asarray(np.array([0xA7, 0x3C], dtype=np.uint8))
    out = np.asarray(U.unpack(data, 4))
    np.testing.assert_array_equal(out, [0xA, 0x7, 0x3, 0xC])


@pytest.mark.parametrize("nbits", [1, 2, 4, 8, -8, 16, -16, 32])
def test_unpack_random_vs_oracle(nbits):
    rng = np.random.default_rng(42)
    data = rng.integers(0, 256, size=1 << 12, dtype=np.uint8)
    expected = U.unpack_oracle(data, nbits)
    got = np.asarray(U.unpack(jnp.asarray(data), nbits))
    np.testing.assert_array_equal(got, expected)


def test_unpack_window_fusion():
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=256, dtype=np.uint8)
    window = rng.random(256 * 4).astype(np.float32)
    expected = U.unpack_oracle(data, 2) * window
    got = np.asarray(U.unpack(jnp.asarray(data), 2, jnp.asarray(window)))
    np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_unpack_interleaved_2pol():
    # "1212" layout (ref: unpack.hpp:214-244)
    data = np.array([1, 101, 2, 102, 3, 103, 4, 104], dtype=np.uint8)
    out1, out2 = U.unpack_interleaved_2pol(jnp.asarray(data), 8)
    np.testing.assert_array_equal(np.asarray(out1), [1, 2, 3, 4])
    np.testing.assert_array_equal(np.asarray(out2), [101, 102, 103, 104])


def test_unpack_naocpsr_snap1():
    # "1122" layout (ref: unpack.hpp:253-283)
    data = np.array([1, 2, 101, 102, 3, 4, 103, 104], dtype=np.uint8)
    out1, out2 = U.unpack_naocpsr_snap1(jnp.asarray(data), 8)
    np.testing.assert_array_equal(np.asarray(out1), [1, 2, 3, 4])
    np.testing.assert_array_equal(np.asarray(out2), [101, 102, 103, 104])


def test_unpack_gznupsr_a1():
    # 4-way word interleave with XOR 0x80 (ref: unpack.hpp:291-328)
    word = np.arange(16, dtype=np.uint8)  # streams of 4 words each
    data = np.concatenate([word, word + 16])
    outs = U.unpack_gznupsr_a1(jnp.asarray(data))
    assert len(outs) == 4
    for i, out in enumerate(outs):
        expected_bytes = np.concatenate([
            (word[4 * i:4 * i + 4] ^ 0x80).view(np.int8),
            ((word + 16)[4 * i:4 * i + 4] ^ 0x80).view(np.int8)])
        np.testing.assert_array_equal(np.asarray(out),
                                      expected_bytes.astype(np.float32))


def test_unpack_gznupsr_a1_v2_1():
    # 2-way word interleave, signed (ref: unpack.hpp:336-369)
    data = np.arange(16, dtype=np.uint8)
    out1, out2 = U.unpack_gznupsr_a1_v2_1(jnp.asarray(data))
    np.testing.assert_array_equal(np.asarray(out1),
                                  [0, 1, 2, 3, 8, 9, 10, 11])
    np.testing.assert_array_equal(np.asarray(out2),
                                  [4, 5, 6, 7, 12, 13, 14, 15])


def test_unpack_float64_bit_decode_without_x64():
    """64-bit float ingest (ref: config.hpp:92-97 allows 32/64-bit
    floating input) decoded from the raw bit pattern: without x64,
    jnp's .view(float64) silently truncates to a float32 view (doubling
    the sample count and corrupting every value — the round-3 stress
    sweep caught exactly that), so the double is reassembled from its
    uint32 halves with an exact bitcast power of two."""
    rng = np.random.default_rng(2)
    with np.errstate(over="ignore"):
        vals = np.concatenate([
            rng.standard_normal(512) * 10 ** rng.uniform(-38, 38, 512),
            [0.0, -0.0, 1.0, -1.0, np.inf, -np.inf, np.nan,
             np.finfo(np.float64).max, np.finfo(np.float64).tiny],
        ]).astype(np.float64)
        want = vals.astype(np.float32)
    raw = jnp.asarray(np.frombuffer(vals.tobytes(), dtype=np.uint8))
    got = np.asarray(U.unpack(raw, 64))
    assert got.shape == want.shape
    for i in range(vals.size):
        w, g = want[i], got[i]
        if (w == g) or (np.isnan(w) and np.isnan(g)):
            continue
        if np.isfinite(w) and np.isfinite(g) \
                and abs(g - w) <= abs(np.spacing(w)):
            continue  # 1-ulp rounding-mode difference
        if g == 0.0 and abs(float(vals[i])) < 2.0 ** -126:
            continue  # f32-subnormal doubles flush to 0 (documented)
        raise AssertionError((i, vals[i], w, g))
