"""Test configuration: run JAX on a virtual 8-device CPU mesh.

This mirrors the reference's CI strategy of testing multi-backend code on
CPU-only runners (ref: .circleci/config.yml, SURVEY.md §4): CPU JAX is the
"fake backend"; multi-chip sharding logic is validated on
``--xla_force_host_platform_device_count=8`` virtual devices.
"""

import os

# SRTB_TEST_TPU=1 keeps the session on the real accelerator so the
# non-interpret Pallas cases run on actual hardware (Mosaic lowering);
# intended for targeted runs (pytest tests/test_pallas_kernels.py), not
# the full suite — multi-device mesh tests need the 8-device CPU mesh.
if not os.environ.get("SRTB_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

    # some environments force a TPU platform plugin via jax.config at
    # interpreter startup (sitecustomize); programmatic config wins over
    # env vars, so force it back to CPU the same way before any backend
    # is initialized.
    import jax  # noqa: E402

    jax.config.update("jax_platforms", "cpu")
