"""Signal-detection tests against the numpy oracle (detect_oracle mirrors
signal_detect_pipe_2, ref: pipeline/signal_detect_pipe.hpp:244-443)."""

import jax
import jax.numpy as jnp
import numpy as np

from srtb_tpu.ops import detect as det


def _make_waterfall(nfreq=64, ntime=1024, pulse_at=None, pulse_width=1,
                    pulse_amp=10.0, seed=0):
    rng = np.random.default_rng(seed)
    wf = (rng.standard_normal((nfreq, ntime))
          + 1j * rng.standard_normal((nfreq, ntime))).astype(np.complex64)
    if pulse_at is not None:
        wf[:, pulse_at:pulse_at + pulse_width] *= pulse_amp
    return wf


def test_count_signal_matches_oracle():
    rng = np.random.default_rng(2)
    x = rng.standard_normal(4096).astype(np.float32)
    x -= x.mean()
    count, peak = jax.jit(det.count_signal, static_argnums=1)(
        jnp.asarray(x), 4.0)
    thr = 4.0 * np.sqrt(np.mean(x.astype(np.float64) ** 2))
    assert int(count) == int(np.sum(x > thr))
    assert abs(float(peak) - x.max() / np.sqrt(np.mean(x ** 2))) < 1e-3


def test_detect_no_signal():
    wf = _make_waterfall()
    res = det.detect(jnp.asarray(wf), time_reserved_count=0,
                     snr_threshold=8.0, max_boxcar_length=64)
    counts = np.asarray(res.signal_counts)
    assert counts.sum() == 0


def test_detect_single_pulse():
    wf = _make_waterfall(pulse_at=500, pulse_amp=6.0)
    res = det.detect(jnp.asarray(wf), time_reserved_count=0,
                     snr_threshold=6.0, max_boxcar_length=64)
    counts = np.asarray(res.signal_counts)
    assert counts[0] >= 1  # boxcar length 1 catches it


def test_detect_wide_pulse_needs_boxcar():
    """A broad weak pulse is invisible at boxcar 1 but detected after
    matched filtering (the reason the reference runs the cascade,
    ref: signal_detect_pipe.hpp:368-424)."""
    wf = _make_waterfall(nfreq=32, ntime=8192, pulse_at=1000,
                         pulse_width=256, pulse_amp=1.25, seed=3)
    res = det.detect(jnp.asarray(wf), time_reserved_count=0,
                     snr_threshold=6.0, max_boxcar_length=512)
    counts = np.asarray(res.signal_counts)
    lengths = res.boxcar_lengths
    wide = sum(int(c) for length, c in zip(lengths, counts) if length >= 128)
    assert wide > 20, f"lengths={lengths} counts={counts.tolist()}"
    assert wide > 10 * counts[0], "matched filter must dominate boxcar 1"


def test_detect_matches_oracle():
    wf = _make_waterfall(nfreq=16, ntime=512, pulse_at=100, pulse_amp=4.0,
                         seed=7)
    wf[3] = 0  # one zapped channel
    reserved = 32 * 16  # nsamps_reserved -> 32 time samples trimmed
    res = det.detect(jnp.asarray(wf), time_reserved_count=32,
                     snr_threshold=5.0, max_boxcar_length=64)
    zero_count, ts, lengths, counts = det.detect_oracle(
        wf, 32, 5.0, 64)
    del reserved
    assert int(res.zero_count) == zero_count == 1
    assert res.boxcar_lengths == lengths
    np.testing.assert_allclose(np.asarray(res.time_series), ts, rtol=2e-4,
                               atol=2e-2)
    np.testing.assert_array_equal(np.asarray(res.signal_counts), counts)


def test_detect_jit_compiles_once():
    wf = _make_waterfall(nfreq=8, ntime=256)
    fn = jax.jit(det.detect, static_argnums=(1, 2, 3))
    r1 = fn(jnp.asarray(wf), 0, 6.0, 16)
    r2 = fn(jnp.asarray(wf * 2), 0, 6.0, 16)
    assert np.asarray(r1.signal_counts).shape == \
        np.asarray(r2.signal_counts).shape
