"""Compile-time HLO plan auditor (srtb_tpu/analysis/hlo_audit.py +
python -m srtb_tpu.tools.plan_audit): donation proven honored vs
visibly dropped, audited spectrum passes vs the declared hbm_passes
floor, dtype/transfer flags, baseline accept/reject, CLI exit codes.

Everything here lowers + compiles on the CPU backend; no program is
ever executed (the auditor's contract: no device required).
"""

import json
import os

import jax
import jax.numpy as jnp
import pytest

from srtb_tpu.analysis import hlo_audit as HA
from srtb_tpu.tools import plan_audit as CLI

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKED_IN = os.path.join(REPO, "srtb_tpu", "analysis",
                          "plan_cards.json")


def _spec(key):
    return next(s for s in HA.PLAN_FAMILIES if s.key == key)


# cards are compile-derived and deterministic — build the expensive
# ones once per module
@pytest.fixture(scope="module")
def staged_proc():
    return HA.build_plan(_spec("staged"))


@pytest.fixture(scope="module")
def family_cards():
    return HA.audit_families(
        ["monolithic", "four_step_ftail", "pallas_skzap"])


# ---------------------------------------------------------- donation


class TestDonation:
    def test_staged_donation_proven_aliased(self, staged_proc):
        """The canonical [2, S, F, T] boundary makes stage_b/stage_c
        donation a REAL XLA input->output alias, visible in the
        compiled artifact's input_output_alias table."""
        card = HA.audit_processor(staged_proc)
        for name in ("stage_b", "stage_c"):
            prog = card["programs"][name]
            assert prog["donation"]["aliased"] == [0], (name, prog)
            assert prog["donation"]["dropped"] == []
            boundary_bytes = 8 * staged_proc.n_spectrum
            assert prog["alias_bytes"] >= boundary_bytes, (name, prog)
        assert card["checks"]["donation_ok"]

    def test_raw_input_donation_is_structural_no_candidate(self):
        """The fused plan's donated raw uint8 buffer can never alias an
        f32 output — the audit records that honestly instead of calling
        it honored OR failing the plan."""
        proc = HA.build_plan(_spec("four_step_ftail_donate"))
        card = HA.audit_processor(proc)
        don = card["programs"]["fused"]["donation"]
        assert don["declared"] == [0]
        assert don["no_candidate"] == [0] and don["aliased"] == []
        assert card["checks"]["donation_ok"]  # no_candidate != dropped

    def test_dropped_donation_is_visible(self, staged_proc):
        """Deliberately disabling donation (a non-donating wrapper of
        the same stage) must visibly change the audited card — the
        regression the CI diff exists to catch."""
        progs = {p[0]: p for p in staged_proc.lowerables()}
        _, fn, args, donated = progs["stage_b"]
        sbytes = 8 * staged_proc.n_spectrum
        honored = HA.audit_program(fn, args, donated, sbytes)
        undonated = HA.audit_program(jax.jit(staged_proc._stage_b),
                                     args, (), sbytes)
        assert honored["donation"]["aliased"] == [0]
        assert undonated["donation"]["declared"] == []
        assert undonated["alias_bytes"] == 0
        assert honored["donation"] != undonated["donation"]

    def test_selftest_catches_both_injections(self):
        assert HA.selftest() == []

    def test_aot_active_processor_still_audits(self, tmp_path):
        """enable_aot swaps the _jit_* attributes for Compiled
        executables (no .lower()); lowerables() must keep handing the
        auditor lowerable wrappers (SRTB_BENCH_AOT_DIR +
        SRTB_BENCH_AUDIT together)."""
        proc = HA.build_plan(_spec("four_step_ftail"))
        assert proc.enable_aot(str(tmp_path), allow_cpu=True)
        card = HA.audit_processor(proc)
        assert card["checks"]["hbm_floor_ok"]

    def test_non_dividing_channel_count_staged(self):
        """channel_count that does not divide n_spectrum (waterfall
        truncates the spectrum tail): the staged boundary falls back to
        the flat canonical [2, S, m] — the chain still runs, stage_b
        still aliases its donation, stage_c's is an honest
        no_candidate."""
        import numpy as np

        from srtb_tpu.pipeline.segment import SegmentProcessor
        cfg = HA._audit_config(14, 12, {"fft_strategy": "four_step",
                                        "fused_tail": "on"})
        proc = SegmentProcessor(cfg, staged=True, donate_input=False)
        assert proc.channel_count * proc.watfft_len != proc.n_spectrum
        card = HA.audit_processor(proc)
        b = card["programs"]["stage_b"]["donation"]
        c = card["programs"]["stage_c"]["donation"]
        assert b["aliased"] == [0] and b["dropped"] == []
        assert c["no_candidate"] == [0] and c["dropped"] == []
        raw = np.random.default_rng(0).integers(
            0, 256, cfg.segment_bytes(1), dtype=np.uint8)
        wf, res = proc.process(raw)
        assert wf.shape[2] == 12  # truncated waterfall, F=12


# ----------------------------------------------- hbm_passes agreement


class TestHbmPasses:
    def test_declared_floor_per_family(self, family_cards):
        """The plan families declare the documented spectrum-pass
        floors (monolithic 7, fused tail 5, fully fused skzap 4) and
        the compiled artifacts sweep at least that much."""
        declared = {k: c["declared_hbm_passes"]
                    for k, c in family_cards.items()}
        assert declared == {"monolithic": 7, "four_step_ftail": 5,
                            "pallas_skzap": 4}
        for key, card in family_cards.items():
            assert card["checks"]["hbm_floor_ok"], (key, card)
            assert card["checks"]["declared_matches_family"], key
            assert card["total_spectrum_passes"] >= \
                card["declared_hbm_passes"]

    def test_extra_pass_moves_the_count(self):
        proc = HA.build_plan(_spec("four_step_ftail"))
        (_, fn, args, don), = [p for p in proc.lowerables()
                               if p[0] == "fused"]
        sbytes = 8 * proc.n_spectrum
        clean = HA.audit_program(fn, args, don, sbytes)
        dirty = HA.audit_program(HA.extra_pass_jit(proc), args, don,
                                 sbytes)
        assert dirty["spectrum_passes"] >= clean["spectrum_passes"] + 2

    def test_transfer_and_dtype_clean(self, family_cards):
        for key, card in family_cards.items():
            assert card["checks"]["transfer_free"], (key, card)
            assert card["checks"]["dtype_clean"], (key, card)


# --------------------------------------------------------- HLO flags


class TestFlags:
    def test_f64_flag_positive(self):
        """A program that genuinely lowers f64 ops must be flagged (the
        drift the dtype-drift lint rule guards at source level, proven
        at artifact level here)."""
        with jax.experimental.enable_x64():
            f = jax.jit(lambda x: x * 2.0 + 1.0)
            aval = jax.ShapeDtypeStruct((4096,), jnp.float64)
            prog = HA.audit_program(f, (aval,), (), 8 * 4096)
        assert prog["f64_ops"] > 0

    def test_host_callback_flagged(self):
        """A debug.print smuggled into a jitted program shows up as a
        host callback custom-call -> transfer_free would fail."""
        def g(x):
            jax.debug.print("x0={v}", v=x[0])
            return x * 2

        aval = jax.ShapeDtypeStruct((1024,), jnp.float32)
        prog = HA.audit_program(jax.jit(g), (aval,), (), 8 * 1024)
        assert prog["host_callbacks"], prog["custom_calls"]

    def test_analyze_hlo_counts_copies_and_collectives(self):
        txt = """\
HloModule m, input_output_alias={ {0}: (0, {}, may-alias) }

ENTRY %main (p0: f32[4096]) -> f32[4096] {
  %p0 = f32[4096]{0} parameter(0)
  %c = f32[4096]{0} copy(f32[4096]{0} %p0)
  %ag = f32[4096]{0} all-gather(f32[4096]{0} %c), dimensions={0}
  ROOT %t = f32[4096]{0} transpose(f32[4096]{0} %ag), dimensions={0}
}
"""
        a = HA.analyze_hlo(txt, 4096 * 4)
        assert a["entry_copies"] == 1
        assert a["entry_transposes"] == 1
        assert a["collectives"] == ["all-gather"]
        assert a["aliased_params"] == [0]
        # copy r+w, all-gather r+w, transpose r+w = 6 unit sweeps
        assert a["spectrum_passes"] == 6

    def test_alias_table_with_multiple_entries(self):
        """Every entry of a multi-donation alias table must parse — a
        lazy regex used to stop at the first entry's inner '{}' and
        misclassify later aliased params as dropped."""
        txt = ("HloModule m, input_output_alias={ {0}: (0, {}, "
               "may-alias), {1}: (2, {}, must-alias) }, "
               "entry_computation_layout={(f32[8])->f32[8]}\n")
        assert HA.analyze_hlo(txt, 1 << 30)["aliased_params"] == [0, 2]


# -------------------------------------------------- baseline + diff


class TestBaseline:
    def test_accept_then_clean_diff(self, family_cards, tmp_path):
        path = str(tmp_path / "cards.json")
        HA.CardBaseline.from_cards(family_cards).save(path)
        regs, new, stale = HA.diff_cards(family_cards,
                                         HA.CardBaseline.load(path))
        assert regs == [] and new == [] and stale == []

    def test_reject_on_mutated_count(self, family_cards, tmp_path):
        path = str(tmp_path / "cards.json")
        HA.CardBaseline.from_cards(family_cards).save(path)
        data = json.load(open(path))
        card = data["cards"]["four_step_ftail"]
        card["programs"]["fused"]["spectrum_passes"] -= 1
        json.dump(data, open(path, "w"))
        regs, _, _ = HA.diff_cards(family_cards,
                                   HA.CardBaseline.load(path))
        assert regs and "spectrum_passes" in regs[0]

    def test_reject_on_donation_change(self, family_cards, tmp_path):
        path = str(tmp_path / "cards.json")
        HA.CardBaseline.from_cards(family_cards).save(path)
        data = json.load(open(path))
        don = data["cards"]["monolithic"]["programs"]["fused"]["donation"]
        don["declared"] = [0]
        json.dump(data, open(path, "w"))
        regs, _, _ = HA.diff_cards(family_cards,
                                   HA.CardBaseline.load(path))
        assert any("donation" in r for r in regs), regs

    def test_new_and_stale_plans_reported(self, family_cards, tmp_path):
        path = str(tmp_path / "cards.json")
        subset = {"monolithic": family_cards["monolithic"]}
        HA.CardBaseline.from_cards(subset).save(path)
        regs, new, stale = HA.diff_cards(family_cards,
                                         HA.CardBaseline.load(path))
        assert set(new) == {"four_step_ftail", "pallas_skzap"}
        b2 = HA.CardBaseline.from_cards(family_cards)
        _, _, stale2 = HA.diff_cards(subset, b2)
        assert set(stale2) == {"four_step_ftail", "pallas_skzap"}

    def test_notes_carried_forward(self, family_cards, tmp_path):
        path = str(tmp_path / "cards.json")
        b = HA.CardBaseline.from_cards(family_cards)
        b.notes["monolithic"] = "why this card is accepted"
        b.save(path)
        old = HA.CardBaseline.load(path)
        HA.CardBaseline.from_cards(family_cards, old=old).save(path)
        assert HA.CardBaseline.load(path).notes["monolithic"] \
            == "why this card is accepted"

    def test_checked_in_baseline_matches_reality(self):
        """Acceptance gate: the real tree's plan cards match the
        checked-in baseline and every invariant check passes — the
        exact invocation ci.sh gates on (subset keeps it fast; the CI
        stage audits all families)."""
        keys = ["monolithic", "four_step_ftail", "staged"]
        cards = HA.audit_families(keys)
        assert HA.failed_checks(cards) == []
        regs, new, _ = HA.diff_cards(cards,
                                     HA.CardBaseline.load(CHECKED_IN))
        assert regs == [], "\n".join(regs)
        assert new == []


# --------------------------------------------------------------- CLI


class TestCli:
    def test_list_plans(self, capsys):
        assert CLI.main(["--list-plans"]) == 0
        out = capsys.readouterr().out
        for key in ("monolithic", "staged", "pallas_skzap"):
            assert key in out

    def test_unknown_plan_is_usage_error(self):
        assert CLI.main(["--plans", "definitely_not_a_plan"]) == 2

    def test_clean_run_exit_zero_and_json(self, capsys):
        rc = CLI.main(["--plans", "monolithic", "--format", "json"])
        data = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert data["regressions"] == [] and data["failed_checks"] == []
        assert data["cards"]["monolithic"]["declared_hbm_passes"] == 7

    def test_regression_exit_one(self, tmp_path, capsys):
        src = json.load(open(CHECKED_IN))
        src["cards"]["monolithic"]["programs"]["fused"][
            "spectrum_passes"] += 1  # "an extra spectrum-sized pass"
        path = str(tmp_path / "cards.json")
        json.dump(src, open(path, "w"))
        rc = CLI.main(["--plans", "monolithic", "--baseline", path])
        assert rc == 1
        assert "spectrum_passes" in capsys.readouterr().out

    def test_unbaselined_plan_exit_one(self, tmp_path, capsys):
        path = str(tmp_path / "empty.json")
        json.dump({"version": 1, "cards": {}, "notes": {}},
                  open(path, "w"))
        rc = CLI.main(["--plans", "monolithic", "--baseline", path])
        assert rc == 1
        assert "not in baseline" in capsys.readouterr().out

    def test_write_baseline_roundtrip(self, tmp_path, capsys):
        path = str(tmp_path / "cards.json")
        assert CLI.main(["--plans", "monolithic",
                         "--write-baseline", "--baseline", path]) == 0
        capsys.readouterr()
        assert CLI.main(["--plans", "monolithic",
                         "--baseline", path]) == 0
