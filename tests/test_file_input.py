"""File reader tests: offset skip, overlap-save positions, zero-padded
tail (ref semantics: read_file_pipe.hpp:38-117)."""

import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.io.file_input import BasebandFileReader
from srtb_tpu.ops import dedisperse as dd


def _write(tmp_path, data):
    path = str(tmp_path / "in.bin")
    np.asarray(data, dtype=np.uint8).tofile(path)
    return path


def test_offset_skip(tmp_path):
    data = np.arange(64, dtype=np.uint8)
    cfg = Config(baseband_input_count=16, baseband_input_bits=8,
                 input_file_path=_write(tmp_path, data),
                 input_file_offset_bytes=10,
                 baseband_reserve_sample=False)
    reader = BasebandFileReader(cfg)
    seg = next(reader)
    np.testing.assert_array_equal(seg.data, data[10:26])


def test_overlap_save_positions(tmp_path):
    """With reserve enabled, consecutive segments must overlap by exactly
    nsamps_reserved samples."""
    n = 1 << 18
    cfg = Config(baseband_input_count=n, baseband_input_bits=8,
                 baseband_freq_low=1405.0, baseband_bandwidth=64.0,
                 baseband_sample_rate=128e6, dm=0.5,
                 spectrum_channel_count=1 << 4,
                 baseband_reserve_sample=True)
    reserved = dd.nsamps_reserved(cfg)
    assert 0 < reserved < n
    data = np.arange(3 * n, dtype=np.uint64).astype(np.uint8)  # wrapping ramp
    data = np.arange(3 * n) % 251
    data = data.astype(np.uint8)
    cfg = cfg.replace(input_file_path=_write(tmp_path, data))
    reader = BasebandFileReader(cfg)
    seg1 = next(reader)
    seg2 = next(reader)
    np.testing.assert_array_equal(seg1.data, data[:n])
    start2 = n - reserved
    np.testing.assert_array_equal(seg2.data, data[start2:start2 + n])


def test_zero_padded_tail(tmp_path):
    data = np.full(24, 7, dtype=np.uint8)
    cfg = Config(baseband_input_count=16, baseband_input_bits=8,
                 input_file_path=_write(tmp_path, data),
                 baseband_reserve_sample=False)
    reader = BasebandFileReader(cfg)
    seg1 = next(reader)
    seg2 = next(reader)
    np.testing.assert_array_equal(seg1.data, 7)
    np.testing.assert_array_equal(seg2.data[:8], 7)
    np.testing.assert_array_equal(seg2.data[8:], 0)  # memset-style padding
    try:
        next(reader)
        raised = False
    except StopIteration:
        raised = True
    assert raised


def test_sub_byte_segment_bytes(tmp_path):
    """2-bit samples: segment bytes = count/4."""
    data = np.arange(32, dtype=np.uint8)
    cfg = Config(baseband_input_count=64, baseband_input_bits=2,
                 input_file_path=_write(tmp_path, data),
                 baseband_reserve_sample=False)
    reader = BasebandFileReader(cfg)
    seg = next(reader)
    assert seg.data.shape == (16,)
    np.testing.assert_array_equal(seg.data, data[:16])
