"""Front-fused staged megakernel tests (the staged_ffuse plan family:
ops/pallas_fft2 pass1_front / pass2_spectrum + pipeline/segment.py
front_fuse wiring + the registry's front_fuse demotion rung).

Acceptance coverage of ISSUE 15:
- detections bit-identical ffuse vs the staged plan across unpack
  variants (1/2/4/8-bit simple, 2-pol byte-interleaved) x ring/cold x
  skzap, with float outputs at the documented fused-plan tolerance
  (test_fusion.py precedent — the two plans run different FFT
  factorizations at CI shapes, so decision equality is the bitwise
  contract and the waterfall/time series are allclose);
- the kernel-level bitwise contract: pass1_front == XLA unpack +
  window + pack_even_odd + pass1_2d, bit for bit (same DFT body on
  identical values);
- the ring-carry alias surviving the fusion, both in the checked-in
  plan cards and in a live audit;
- the ladder demoting ffuse -> today's staged plan on an injected
  Mosaic compile fault;
- signature / cache key / plan name distinguishing the family.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from srtb_tpu.config import Config
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.ops import fft as F
from srtb_tpu.ops import pallas_fft2 as pf2
from srtb_tpu.ops import rfi
from srtb_tpu.ops import unpack as U
from srtb_tpu.ops import window as W
from srtb_tpu.pipeline.segment import (SegmentProcessor,
                                       front_fuse_resolves,
                                       waterfall_to_numpy)
from srtb_tpu.utils.metrics import metrics

N = 1 << 16
M = N // 2


@pytest.fixture(autouse=True)
def _pallas2_rows(monkeypatch):
    """Every test in this file runs the staged plan on pallas2 rows —
    the front-fuse prerequisite."""
    monkeypatch.setenv("SRTB_STAGED_ROWS_IMPL", "pallas2")


def _base(**extra):
    cfg = dict(baseband_input_count=N, baseband_input_bits=2,
               baseband_format_type="simple", baseband_freq_low=1405.0,
               baseband_bandwidth=64.0, baseband_sample_rate=128e6,
               dm=30.0, spectrum_channel_count=8,
               mitigate_rfi_average_method_threshold=25.0,
               mitigate_rfi_spectral_kurtosis_threshold=5.0,
               signal_detect_signal_noise_threshold=5.0,
               signal_detect_max_boxcar_length=8,
               mitigate_rfi_freq_list="1410-1412",
               baseband_reserve_sample=False,
               fft_strategy="four_step", fused_tail="on")
    cfg.update(extra)
    return Config(**cfg)


def _raw(nbits, streams=1, seed=0, amp=8.0):
    if streams == 1:
        return make_dispersed_baseband(
            N, 1405.0, 64.0, 30.0, pulse_positions=N // 2,
            pulse_amp=amp, nbits=nbits, seed=seed)
    # 2-pol byte interleave: two independent 8-bit streams, bytes
    # alternating "1212" (ops/unpack.unpack_interleaved_2pol)
    a = make_dispersed_baseband(N, 1405.0, 64.0, 30.0,
                                pulse_positions=N // 2, pulse_amp=amp,
                                nbits=nbits, seed=seed)
    b = make_dispersed_baseband(N, 1405.0, 64.0, 30.0,
                                pulse_positions=N // 3, pulse_amp=amp,
                                nbits=nbits, seed=seed + 1)
    out = np.empty(a.size + b.size, dtype=np.uint8)
    out[0::2] = a
    out[1::2] = b
    return out


def _assert_parity(proc_a, proc_b, raw, ts_atol=1e-3):
    wf_a, res_a = proc_a.process(raw)
    wf_b, res_b = proc_b.process(raw)
    np.testing.assert_array_equal(np.asarray(res_a.signal_counts),
                                  np.asarray(res_b.signal_counts))
    np.testing.assert_array_equal(np.asarray(res_a.zero_count),
                                  np.asarray(res_b.zero_count))
    a = waterfall_to_numpy(wf_b)
    b = waterfall_to_numpy(wf_a)
    scale = float(np.abs(a).max())
    assert scale > 0, "all waterfall rows zapped — test data too hot"
    np.testing.assert_allclose(b, a, atol=ts_atol * scale, rtol=0)
    ts_a = np.asarray(res_a.time_series)
    ts_b = np.asarray(res_b.time_series)
    np.testing.assert_allclose(
        ts_a, ts_b, rtol=0,
        atol=ts_atol * (float(np.abs(ts_b).max()) or 1.0))
    return res_a, res_b


# -------------------------------------------------- plan-level parity


@pytest.mark.parametrize("nbits", [1, 2, 4, 8])
def test_parity_vs_staged_simple(nbits):
    cfg = _base(baseband_input_bits=nbits)
    ff = SegmentProcessor(Config(**{**cfg.__dict__,
                                    "front_fuse": "on"}), staged=True)
    st = SegmentProcessor(Config(**{**cfg.__dict__,
                                    "front_fuse": "off"}), staged=True)
    assert ff.front_fuse and not st.front_fuse
    assert ff.hbm_passes == 2 and ff.plan_name.endswith("+ffuse")
    _assert_parity(ff, st, _raw(nbits))


def test_parity_vs_staged_interleaved_2pol():
    cfg = _base(baseband_input_bits=8,
                baseband_format_type="interleaved_samples_2")
    ff = SegmentProcessor(Config(**{**cfg.__dict__,
                                    "front_fuse": "on"}), staged=True)
    st = SegmentProcessor(Config(**{**cfg.__dict__,
                                    "front_fuse": "off"}), staged=True)
    assert ff.data_stream_count == 2
    res_f, _ = _assert_parity(ff, st, _raw(8, streams=2))
    assert np.asarray(res_f.signal_counts).shape[0] == 2


def test_parity_windowed():
    """Windowed front: the even/odd window operands reach the kernel
    and stage (b)'s dedispersed spectrum matches the staged plan's.
    (Compared at the spectrum boundary: at this tiny shape the hann
    dewindow's near-zero edges blow up every waterfall row's kurtosis
    and BOTH plans SK-zap the whole waterfall — a data artifact, not a
    plan difference, so downstream decisions are vacuously equal.)"""
    cfg = _base()
    ff = SegmentProcessor(Config(**{**cfg.__dict__,
                                    "front_fuse": "on"}),
                          window_name="hann", staged=True)
    st = SegmentProcessor(Config(**{**cfg.__dict__,
                                    "front_fuse": "off"}),
                          window_name="hann", staged=True)
    assert ff._ffuse_window is not None
    raw = _raw(2)
    spec_f = np.asarray(ff._run_stage_b(ff._jit_stage_a(
        ff._as_device_bytes(raw))))
    n1, n2 = ff._ffuse_fac
    # unblock ffuse's k1-major spectrum to natural order
    spec_f = np.swapaxes(spec_f.reshape(2, -1, n1, n2), -1, -2) \
        .reshape(2, -1, M)
    spec_s = np.asarray(st._run_stage_b(st._jit_stage_a(
        st._as_device_bytes(raw)))).reshape(2, -1, M)
    scale = np.abs(spec_s).max()
    assert scale > 0
    np.testing.assert_allclose(spec_f, spec_s, atol=1e-4 * scale,
                               rtol=0)


def test_parity_skzap_combo():
    """The fully front-AND-back-fused staged plan: ffuse front + the
    one-kernel skzap waterfall tail.  hbm_passes stays the 2-sweep
    front floor; decisions match the non-skzap ffuse plan."""
    cfg = _base(use_pallas=True, use_pallas_sk=True)
    ff_sk = SegmentProcessor(Config(**{**cfg.__dict__,
                                       "front_fuse": "on"}),
                             staged=True)
    ff = SegmentProcessor(Config(**{**_base().__dict__,
                                    "front_fuse": "on"}), staged=True)
    assert ff_sk._skzap and ff_sk.plan_name.endswith("+ffuse+skzap")
    assert ff_sk.hbm_passes == 2
    _assert_parity(ff_sk, ff, _raw(2))


# ------------------------------------------------ kernel-level checks


def test_pass1_front_bitwise_vs_xla_pack():
    """The in-kernel unpack + window + even/odd pack feeds the SAME
    column-DFT body as the packed path — on identical exact-integer
    inputs the blocked intermediate must match BIT FOR BIT."""
    n1, n2 = pf2.ffuse_factor(M)
    rng = np.random.default_rng(3)
    raw = rng.integers(0, 256, size=N * 2 // 8, dtype=np.uint8)
    win = W.window_coefficients("hamming", N)
    x = U.unpack(jnp.asarray(raw), 2, jnp.asarray(win))
    z = F.pack_even_odd(x)
    br_ref, bi_ref = pf2.pass1_2d(jnp.real(z).reshape(n1, n2),
                                  jnp.imag(z).reshape(n1, n2),
                                  interpret=True)
    w_eo = (jnp.asarray(np.ascontiguousarray(win[0::2].reshape(n1, n2))),
            jnp.asarray(np.ascontiguousarray(win[1::2].reshape(n1, n2))))
    br, bi, _ = pf2.pass1_front(jnp.asarray(raw), m=M, streams=1,
                                variant="simple", nbits=2,
                                window_eo=w_eo, interpret=True)
    np.testing.assert_array_equal(np.asarray(br[0]), np.asarray(br_ref))
    np.testing.assert_array_equal(np.asarray(bi[0]), np.asarray(bi_ref))


def test_front_mean_matches_packed():
    """The pass-1 accumulators' Parseval mean agrees with
    rfi.mean_power_packed over the materialized C2C to f32 rounding."""
    rng = np.random.default_rng(5)
    raw = rng.integers(0, 256, size=N * 2 // 8, dtype=np.uint8)
    _, _, aux = pf2.pass1_front(jnp.asarray(raw), m=M, streams=1,
                                variant="simple", nbits=2,
                                interpret=True)
    z = F.pack_even_odd(U.unpack(jnp.asarray(raw), 2, None))
    ref = float(rfi.mean_power_packed(jnp.fft.fft(z))[0])
    got = float(pf2.front_mean_power(aux, pf2.ffuse_factor(M)[1], M)[0])
    assert abs(got - ref) <= 1e-5 * abs(ref)


def test_pass2_premul_matches_reference():
    """The chirp-twiddle premul bank (SegmentProcessor._premul_bank
    cw = c*w) folded into pass 2's epilogue matches
    hermitian_rfft_post(premul=...) + s1 on the same intermediate."""
    from srtb_tpu.ops import dedisperse as dd
    n1, n2 = pf2.ffuse_factor(M)
    rng = np.random.default_rng(7)
    zr = jnp.asarray(rng.standard_normal(M).astype(np.float32))
    zi = jnp.asarray(rng.standard_normal(M).astype(np.float32))
    br, bi = pf2.pass1_2d(zr.reshape(n1, n2), zi.reshape(n1, n2),
                          interpret=True)
    yr, yi = pf2.pass2_2d(br, bi, interpret=True)
    zf = jnp.asarray((np.asarray(yr) + 1j * np.asarray(yi))
                     .T.reshape(M).astype(np.complex64))
    mean = float(rfi.mean_power_packed(zf[None])[0, 0])
    c_ri = dd.chirp_factor_df64_ri(M, 1405.0, 64.0 / M, 1437.0, 30.0)
    c = (np.asarray(c_ri[0]) + 1j * np.asarray(c_ri[1]))
    cw = c * np.asarray(F._iota_phase(M, 2 * M, -1.0))

    def blocked(a):
        return jnp.asarray(np.ascontiguousarray(
            a.astype(np.float32).reshape(n2, n1).T))

    pm = (blocked(c.real), blocked(c.imag),
          blocked(cw.real), blocked(cw.imag))
    sr, si = pf2.pass2_spectrum(br, bi, thr=jnp.float32(1.5 * mean),
                                norm=0.125, premul_blocked=pm,
                                interpret=True)
    got = (np.asarray(sr) + 1j * np.asarray(si)).T.reshape(M)
    ref = F.hermitian_rfft_post(
        zf, drop_nyquist=True,
        premul=(jnp.asarray(c.astype(np.complex64)),
                jnp.asarray(cw.astype(np.complex64))))
    ref = np.asarray(rfi.mitigate_rfi_s1_given_mean(
        ref, jnp.float32(mean), 1.5, 0.125))
    scale = float(np.abs(ref).max())
    np.testing.assert_allclose(got, ref, atol=2e-5 * scale, rtol=0)


# ------------------------------------------------------- ring variants


def _ring_cfg(front_fuse):
    # small dm keeps 0 < reserved_bytes < segment_bytes at this shape
    return _base(dm=0.1, baseband_input_bits=8,
                 baseband_reserve_sample=True, front_fuse=front_fuse)


def test_ring_warm_cold_bit_identical_to_direct():
    """The ffuse ring variants reassemble bit-identically: a cold
    dispatch then a warm carry ++ stride dispatch reproduce the
    direct full-segment runs exactly (same programs inside)."""
    ff = SegmentProcessor(_ring_cfg("on"), staged=True)
    assert ff.ring and ff.front_fuse
    raw0 = _raw(8, seed=0)
    # overlap-save stream: segment 1 starts at stride offset
    stream = np.concatenate([raw0, _raw(8, seed=1)])
    seg0 = stream[:ff._segment_bytes]
    seg1 = stream[ff.stride_bytes:ff.stride_bytes + ff._segment_bytes]
    (wf0, res0), carry = ff.run_device_cold(jax.device_put(seg0))
    (wf1, res1), _ = ff.run_device_ring(
        carry, jax.device_put(seg1[ff.reserved_bytes:]))
    dwf0, dres0 = ff.run_device(jax.device_put(seg0))
    dwf1, dres1 = ff.run_device(jax.device_put(seg1))
    np.testing.assert_array_equal(np.asarray(wf0), np.asarray(dwf0))
    np.testing.assert_array_equal(np.asarray(wf1), np.asarray(dwf1))
    np.testing.assert_array_equal(np.asarray(res1.signal_counts),
                                  np.asarray(dres1.signal_counts))
    np.testing.assert_array_equal(np.asarray(res0.time_series),
                                  np.asarray(dres0.time_series))


def test_ring_cards_pin_carry_alias():
    """The checked-in ffuse cards: declared floor == 2 pinned, and the
    ring family's warm assemble proves the carry alias survived the
    fusion (aliased param 0, alias_bytes > 0)."""
    from srtb_tpu.analysis.hlo_audit import DEFAULT_BASELINE
    cards = json.load(open(DEFAULT_BASELINE))["cards"]
    for key in ("staged_ffuse", "staged_ffuse_ring"):
        card = cards[key]
        assert card["declared_hbm_passes"] == 2, key
        assert card["plan_name"].startswith("staged:four_step+ftail"
                                            "+ffuse"), key
        assert card["checks"]["hbm_floor_ok"], key
        assert card["checks"]["donation_ok"], key
    ring = cards["staged_ffuse_ring"]
    assert ring["ingest"] == "ring-v1"
    warm = ring["programs"]["stage_a_ring"]
    assert 0 in warm["donation"]["aliased"]
    assert warm["alias_bytes"] > 0
    assert ring["checks"]["ring_alias_ok"]


def test_ring_alias_proven_live():
    """Live audit of a freshly built ffuse+ring processor: every
    invariant check green, incl. the carry alias (the PR-7 aval
    lesson surviving the front fusion)."""
    from srtb_tpu.analysis.hlo_audit import audit_processor
    proc = SegmentProcessor(_ring_cfg("on"), staged=True,
                            donate_input=True)
    card = audit_processor(proc)
    assert all(card["checks"].values()), card["checks"]
    assert card["declared_hbm_passes"] == 2
    assert card["total_spectrum_passes"] >= 2  # the proven floor


# ------------------------------------------------- ladder + identity


def test_ladder_first_rung_drops_front_fuse():
    from srtb_tpu.resilience.demote import ladder_rungs
    cfg = _ring_cfg("on")
    rungs = ladder_rungs(cfg, base_staged=True)
    assert rungs[0].step == "front_fuse"
    assert rungs[0].cfg.front_fuse == "off"
    demoted = SegmentProcessor(rungs[0].cfg, staged=rungs[0].staged)
    assert not demoted.front_fuse
    assert "+ffuse" not in demoted.plan_name  # today's staged plan


def test_compile_fault_demotes_ffuse_to_staged(tmp_path):
    """An injected Mosaic compile fault at dispatch demotes the ffuse
    plan down its rung onto today's staged plan mid-run, with the
    faulted segment re-dispatched from its retained host buffer and
    decisions identical to a fault-free run."""
    from srtb_tpu.pipeline.runtime import Pipeline

    segs = 3
    path = tmp_path / "bb.bin"
    np.concatenate([_raw(8, seed=i) for i in range(segs)]).tofile(path)

    def cfg(tag, **extra):
        return Config(**{
            **_base(baseband_input_bits=8, front_fuse="on").__dict__,
            "input_file_path": str(path),
            "baseband_output_file_prefix": str(tmp_path / f"{tag}_"),
            "writer_thread_count": 0, "inflight_segments": 2,
            "retry_backoff_base_s": 0.001, **extra})

    class Sink:
        def __init__(self):
            self.out = []

        def push(self, work, positive):
            self.out.append(
                (np.asarray(work.detect.signal_counts).copy(),
                 np.asarray(work.detect.zero_count).copy()))

    metrics.reset()
    clean = Sink()
    c0 = cfg("clean", plan_ladder="off")
    with Pipeline(c0, sinks=[clean],
                  processor=SegmentProcessor(c0, staged=True)) as pipe:
        assert pipe.processor.front_fuse
        pipe.run()
    metrics.reset()
    sink = Sink()
    c1 = cfg("cfail", fault_plan="dispatch:compile_fail@1")
    with Pipeline(c1, sinks=[sink],
                  processor=SegmentProcessor(c1, staged=True)) as pipe:
        stats = pipe.run()
        assert pipe.faults.unfired() == []
        assert pipe.healer.level == 1
        assert pipe.healer.active_step == "front_fuse"
        assert not pipe.processor.front_fuse
        assert "+ffuse" not in pipe.processor.plan_name
    assert stats.segments == len(clean.out)
    assert metrics.get("plan_demotions") == 1
    assert metrics.get("segments_dropped") == 0
    for (sc_a, zc_a), (sc_b, zc_b) in zip(sink.out, clean.out):
        np.testing.assert_array_equal(sc_a, sc_b)
        np.testing.assert_array_equal(zc_a, zc_b)
    metrics.reset()


def test_signature_cache_key_and_name_distinguish():
    on_cfg = _base(front_fuse="on")
    off_cfg = _base(front_fuse="off")
    ff = SegmentProcessor(on_cfg, staged=True)
    st = SegmentProcessor(off_cfg, staged=True)
    assert ff.plan_signature() != st.plan_signature()
    assert SegmentProcessor.plan_cache_key(on_cfg) \
        != SegmentProcessor.plan_cache_key(off_cfg)
    assert '"front_fuse": true' in ff.plan_signature()
    assert ff.plan_name == st.plan_name.replace("+ftail",
                                                "+ftail+ffuse")
    # "auto" without the probe flag / env opt-in keeps today's plan
    # (the raw knob still enters the cfg projection, like fused_tail's
    # auto/on — only the RESOLVED plan must stay the staged one)
    auto = SegmentProcessor(_base(front_fuse="auto"), staged=True)
    assert not auto.front_fuse
    assert auto.plan_name == st.plan_name
    assert '"front_fuse": false' in auto.plan_signature()


def test_auto_resolves_on_with_env_opt_in(monkeypatch):
    monkeypatch.setenv("SRTB_PALLAS_FFUSE", "1")
    proc = SegmentProcessor(_base(front_fuse="auto"), staged=True)
    assert proc.front_fuse


def test_front_fuse_on_requires_prerequisites(monkeypatch):
    # not staged
    with pytest.raises(ValueError, match="front_fuse=on"):
        SegmentProcessor(_base(front_fuse="on"), staged=False)
    # wrong rows impl
    monkeypatch.setenv("SRTB_STAGED_ROWS_IMPL", "pallas")
    with pytest.raises(ValueError, match="front_fuse=on"):
        SegmentProcessor(_base(front_fuse="on"), staged=True)
    monkeypatch.setenv("SRTB_STAGED_ROWS_IMPL", "pallas2")
    # unfusable tail (monolithic strategy)
    with pytest.raises(ValueError):
        SegmentProcessor(_base(front_fuse="on", fused_tail="off"),
                         staged=True)
    # unsupported format variant
    with pytest.raises(ValueError, match="front_fuse=on"):
        SegmentProcessor(
            _base(front_fuse="on", baseband_input_bits=-8,
                  baseband_format_type="naocpsr_snap1"), staged=True)
    # pure predicate agrees (the ladder-rung / resolver shared home)
    assert not front_fuse_resolves(_base(front_fuse="auto"), False)
    assert front_fuse_resolves(_base(front_fuse="on"), True)


def test_sanitize_run_handles_tuple_boundary():
    cfg = Config(**{**_base(front_fuse="on").__dict__,
                    "sanitize": True})
    proc = SegmentProcessor(cfg, staged=True, donate_input=True)
    wf, res = proc.process(_raw(2))
    assert np.asarray(res.zero_count).shape == (1,)


def test_ffuse_factor_windows():
    # production window delegates to the standard factorization
    assert pf2.ffuse_factor(1 << 26) == (4096, 1 << 14)
    # CI window gets a small-leg split with n2 >= 128
    n1, n2 = pf2.ffuse_factor(M)
    assert n1 * n2 == M and n2 >= 128
    assert pf2.ffuse_factor(3 * (1 << 12)) is None  # not a power of 2
    assert pf2.ffuse_factor(1 << 6) is None         # too small
