#!/bin/bash
# Round-8 TPU hardware backlog: archive-replay throughput + the
# periodicity/folding search mode, on top of the still-undrained r7
# backlog (ring A/Bs).  The archive legs measure what the replay
# engine exists for — recorded baseband at full device occupancy, no
# real-time pacing, deep micro-batch, files fanned across fleet lanes
# — against the real-time-shaped solo engine on the same bytes; the
# periodicity legs price the harmonic-sum + folding module against the
# single-pulse plan it extends.  Safe to re-run; each block is
# independent.  Run from the repo root with the TPU visible
# (tools_tpu_watcher.sh fires it automatically).
#
#   bash tools_tpu_r8_queue.sh [quick]
#
# "quick" drains only the new r8 rows (skips the r7 backlog and the
# long 2^30 / multi-GB-archive blocks).
set -u
OUT=${SRTB_PERF_OUT:-PERF_TPU.jsonl}
stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }
note() { echo "{\"ts\": \"$(stamp)\", \"variant\": \"note\", \"note\": \"$1\"}" >> "$OUT"; }
run() {
  local tag="$1"; shift
  echo "== $tag =="
  local line
  line=$("$@" 2>/dev/null | grep '^{' | tail -1)
  if [ -n "$line" ]; then
    echo "{\"ts\": \"$(stamp)\", \"variant\": \"$tag\", \"result\": $line}" >> "$OUT"
    echo "$line"
  else
    echo "{\"ts\": \"$(stamp)\", \"variant\": \"$tag\", \"error\": true}" >> "$OUT"
  fi
}

QUICK=${1:-}

# ---- 0. the r7 backlog first (ring A/Bs, never drained) ----
if [ "$QUICK" != "quick" ] && [ -f tools_tpu_r7_queue.sh ]; then
  note "r8 queue: draining r7 backlog first"
  bash tools_tpu_r7_queue.sh quick
fi

note "r8 queue start: archive replay throughput + periodicity search mode"

# ---- 1. periodicity A/B at 2^27: the harmonic-sum + folding module
#          rides the detection time series (2^16 samples at 2^11
#          channels), so its cost should be dispatch-level noise next
#          to the segment FFTs — this pair prices that claim.
run period_off_27 env SRTB_BENCH_LOG2N=27 SRTB_BENCH_FFT_STRATEGY=four_step \
    SRTB_BENCH_DEADLINE=900 python bench.py
run period_on_27  env SRTB_BENCH_LOG2N=27 SRTB_BENCH_FFT_STRATEGY=four_step \
    SRTB_BENCH_SEARCH_MODE=periodicity SRTB_BENCH_DEADLINE=900 python bench.py

# ---- 2. archive replay vs real-time-shaped streaming on the SAME
#          recorded bytes (2^24-sample segments, 8 files x 32
#          segments): replay = fleet-fanned lanes, micro-batch 4,
#          window 8; baseline = the solo serial engine, one file at a
#          time.  seg/s ratio is the engine's payoff number
#          (PERF.md round 16 carries the CPU methodology + noise
#          caveat).
ARCH_DIR=${SRTB_ARCHIVE_DIR:-/tmp/srtb_r8_archive}
python - <<'EOF'
import os
from srtb_tpu.io.synth import make_dispersed_baseband
d = os.environ.get("SRTB_ARCHIVE_DIR", "/tmp/srtb_r8_archive")
os.makedirs(d, exist_ok=True)
n = 1 << 24
for i in range(8):
    p = os.path.join(d, f"arch{i}.bin")
    if not (os.path.exists(p) and os.path.getsize(p) == n * 32):
        make_dispersed_baseband(
            n * 32, 1405.0, 64.0, 0.05,
            pulse_positions=[n // 2 + j * n for j in range(32)],
            pulse_amp=30.0, nbits=8, seed=i).tofile(p)
EOF
run archive_stream_24 python - <<EOF
import glob, json, os, time
from srtb_tpu.config import Config
from srtb_tpu.pipeline.runtime import Pipeline
cfg0 = dict(baseband_input_count=1 << 24, baseband_input_bits=8,
            baseband_freq_low=1405.0, baseband_bandwidth=64.0,
            baseband_sample_rate=128e6, dm=0.05,
            spectrum_channel_count=1 << 11,
            signal_detect_signal_noise_threshold=50.0,
            baseband_reserve_sample=True, writer_thread_count=0,
            fft_strategy="four_step", deterministic_timestamps=True)
t0 = time.perf_counter(); segs = 0
for i, f in enumerate(sorted(glob.glob("$ARCH_DIR/arch*.bin"))):
    cfg = Config(**cfg0).replace(
        input_file_path=f, inflight_segments=2,
        baseband_output_file_prefix=f"$ARCH_DIR/solo{i}_")
    with Pipeline(cfg, sinks=[]) as p:
        segs += p.run().segments
dt = time.perf_counter() - t0
print(json.dumps({"metric": "archive_stream_seg_s",
                  "value": round(segs / dt, 2), "segments": segs,
                  "elapsed_s": round(dt, 1)}))
EOF
run archive_replay_24 python -m srtb_tpu.tools.archive_replay \
    --files "$ARCH_DIR/arch*.bin" --out-dir "$ARCH_DIR/replay" \
    --lanes 4 --micro-batch 4 --inflight 8 --no-waterfall \
    --set baseband_input_count="2 ** 24" --set baseband_input_bits=8 \
    --set baseband_freq_low=1405.0 --set baseband_bandwidth=64.0 \
    --set baseband_sample_rate=128e6 --set dm=0.05 \
    --set spectrum_channel_count="2 ** 11" \
    --set signal_detect_signal_noise_threshold=50.0 \
    --set baseband_reserve_sample=1 --set writer_thread_count=0 \
    --set fft_strategy=four_step

if [ "$QUICK" = "quick" ]; then exit 0; fi

# ---- 3. periodicity at the 2^30 staged production shape: the mode
#          must survive the staged plan's three-program chain (the
#          folding rides stage (c)).
run period_staged_30 env SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 \
    SRTB_BENCH_SEARCH_MODE=periodicity SRTB_BENCH_REPS=3 \
    SRTB_BENCH_DEADLINE=2700 python bench.py

note "r8 queue done"
