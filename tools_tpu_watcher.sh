#!/bin/bash
# Repo-tracked TPU tunnel watcher (round-3 verdict: recovery must not
# depend on a /tmp script surviving a host swap).  Probes the tunnel
# with a bounded subprocess every 4 min; on recovery fires the hardware
# queue once, commits the artifact files, and exits.
#
# Durability caveat: this repo has no git remote, so the auto-commit is
# host-local — it protects the results from session loss, not from a
# host swap after recovery.  (If a remote ever exists, add a push with
# a logged failure fallback after the commit.)
#
#   nohup bash tools_tpu_watcher.sh >/dev/null 2>&1 &   # arm
#   bash ci.sh --hardware                                # same, via CI
#
# Env: SRTB_TPU_QUEUE (default tools_tpu_r10_queue.sh), SRTB_WATCH_LOG.
set -u
cd "$(dirname "$0")"
QUEUE=${SRTB_TPU_QUEUE:-tools_tpu_r10_queue.sh}
LOG=${SRTB_WATCH_LOG:-/tmp/tpu_watcher.log}
PIDFILE=/tmp/tpu_watcher.pid

# single probe body for both the arming check and the post-queue
# re-arm discriminator — two copies would drift
tpu_alive() {
  timeout 150 python -c "
import jax, jax.numpy as jnp
d = jax.devices()[0]
assert d.platform == 'tpu', d.platform
print(float(jax.jit(lambda x: (x*x).sum())(jnp.arange(8.0))))
" >> "$LOG" 2>&1
}

if [ -f "$PIDFILE" ] && kill -0 "$(cat "$PIDFILE")" 2>/dev/null; then
  echo "watcher already running (pid $(cat "$PIDFILE"))" >&2
  exit 0
fi
echo $$ > "$PIDFILE"
echo "$(date -u +%FT%TZ) watcher start (queue: $QUEUE)" >> "$LOG"

# Up to 3 firings: a tunnel that recovers and dies mid-queue leaves
# mostly-error rows behind — keep watching and fire again (the queue is
# idempotent; each block re-measures) instead of exiting after a
# half-dead recovery.
FIRES=0
while true; do
  if tpu_alive; then
    FIRES=$((FIRES + 1))
    echo "$(date -u +%FT%TZ) TPU BACK — firing $QUEUE (attempt $FIRES)" >> "$LOG"
    bash "$QUEUE" >> /tmp/tpu_queue.log 2>&1
    echo "$(date -u +%FT%TZ) queue done rc=$?" >> "$LOG"
    # pathspec form: commit ONLY the artifact files, never whatever else
    # happens to be staged when the watcher fires hours later.  Only
    # name files that exist — one missing pathspec fails the WHOLE
    # commit and would lose the hardware rows.
    ARTS=""
    for f in PERF_TPU.jsonl E2E_LIVE.jsonl DECISIONS_r4.md \
             DECISIONS_r5.md; do
      [ -f "$f" ] && ARTS="$ARTS $f"
    done
    if [ -n "$ARTS" ]; then
      # shellcheck disable=SC2086 # word-splitting is the point
      git add $ARTS 2>/dev/null
      git commit -q -m "Record TPU hardware A/B results (auto-captured on tunnel recovery)" \
          -- $ARTS >> "$LOG" 2>&1
      echo "$(date -u +%FT%TZ) artifacts committed:$ARTS" >> "$LOG"
    fi
    # Distinguish "tunnel died mid-queue" (re-arm and re-measure) from
    # "tunnel healthy, some variants deterministically failed" (done —
    # re-running would burn hardware hours on the same rejections): the
    # discriminator is whether the tunnel answers NOW, after the queue.
    if tpu_alive || [ "$FIRES" -ge 3 ]; then
      rm -f "$PIDFILE"
      exit 0
    fi
    echo "$(date -u +%FT%TZ) tunnel dead after queue — re-arming" >> "$LOG"
  fi
  echo "$(date -u +%FT%TZ) still down" >> "$LOG"
  sleep 240
done
