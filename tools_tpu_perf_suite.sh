#!/bin/bash
# One-shot TPU perf sweep: headline bench + code-path A/Bs + per-kernel
# numbers, appended as JSON lines to PERF_TPU.jsonl with a variant tag.
# Run from the repo root on a machine with the TPU visible.
set -u
OUT=PERF_TPU.jsonl
stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }

run() {
  local tag="$1"; shift
  echo "== $tag =="
  local line
  line=$("$@" 2>/dev/null | grep '^{' | tail -1)
  if [ -n "$line" ]; then
    echo "{\"ts\": \"$(stamp)\", \"variant\": \"$tag\", \"result\": $line}" >> "$OUT"
    echo "$line"
  else
    echo "{\"ts\": \"$(stamp)\", \"variant\": \"$tag\", \"error\": true}" >> "$OUT"
  fi
}

run baseline   python bench.py
# the first run already paid the full probe/retry budget; if the
# accelerator is down the remaining runs should fall back immediately,
# not re-probe a dead tunnel for 15 min each
if tail -1 "$OUT" | grep -Eq '"platform": "cpu"|"value": 0\.0|"error"'; then
  export SRTB_BENCH_RETRY_BUDGET=0
  export SRTB_BENCH_INIT_TIMEOUT=60
fi
run pallas     env SRTB_BENCH_USE_PALLAS=1 python bench.py
run four_step  env SRTB_BENCH_FFT_STRATEGY=four_step python bench.py
run monolithic env SRTB_BENCH_FFT_STRATEGY=monolithic python bench.py
run mxu        env SRTB_BENCH_FFT_STRATEGY=mxu python bench.py
run pallas_fs  env SRTB_BENCH_FFT_STRATEGY=pallas python bench.py
run n2_28      env SRTB_BENCH_LOG2N=28 python bench.py
run n2_29      env SRTB_BENCH_LOG2N=29 python bench.py
# 2^30 (the reference's production segment size) auto-selects the staged
# three-program plan; there is no fused alternative that fits 16 GB HBM
run n2_30      env SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 python bench.py

echo "== kernel bench ==" | tee -a /dev/stderr
python -m srtb_tpu.tools.kernel_bench --log2n 28 --reps 5 2>/dev/null \
  | while read -r line; do
      echo "{\"ts\": \"$(stamp)\", \"variant\": \"kernel\", \"result\": $line}" >> "$OUT"
      echo "$line"
    done
