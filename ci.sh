#!/bin/bash
# One-command reproduction of the verification this repo is judged by
# (L8 parity with the reference's CircleCI matrix,
# ref: /root/reference/.circleci/config.yml — there: 2 toolchains x 2
# arches of the SYCL build + ctest; here: native build + static checks +
# the full pytest suite on the virtual 8-device CPU mesh + the bench and
# multichip dryrun smoke).
#
# Usage: ./ci.sh [--fast]   (--fast skips the slowest pytest cases)
#        ./ci.sh --hardware (arm the TPU watcher: probes the tunnel and
#                            fires the hardware queue on recovery — the
#                            repo-tracked re-arm path, round-3 verdict)
set -euo pipefail
cd "$(dirname "$0")"

if [ "${1:-}" = "--hardware" ]; then
  [ -f tools_tpu_watcher.sh ] || { echo "tools_tpu_watcher.sh missing" >&2; exit 1; }
  if [ -f /tmp/tpu_watcher.pid ] && kill -0 "$(cat /tmp/tpu_watcher.pid)" 2>/dev/null; then
    echo "TPU watcher already running (pid $(cat /tmp/tpu_watcher.pid))"
    exit 0
  fi
  nohup bash tools_tpu_watcher.sh >/dev/null 2>&1 &
  echo "TPU watcher armed (pid $!, log ${SRTB_WATCH_LOG:-/tmp/tpu_watcher.log})"
  exit 0
fi

echo "== [1/23] native build =="
make -C srtb_tpu/native

echo "== [2/23] native sanitizer harness (ASan/UBSan) =="
make -C srtb_tpu/native check

echo "== [3/23] static checks (compile + import) =="
python -m compileall -q srtb_tpu tests bench.py __graft_entry__.py
python - <<'EOF'
import importlib, pkgutil
import srtb_tpu
bad = []
for m in pkgutil.walk_packages(srtb_tpu.__path__, "srtb_tpu."):
    try:
        importlib.import_module(m.name)
    except Exception as e:  # noqa: BLE001 - report every import failure
        bad.append((m.name, e))
assert not bad, bad
print(f"all srtb_tpu modules import cleanly")
EOF

echo "== [4/23] srtb-lint (static analysis vs baseline) =="
# fails on findings not in srtb_tpu/analysis/baseline.json; accept an
# intentional finding with --write-baseline + a note, or a pragma.
# The machine-readable run lands next to the other CI artifacts.
mkdir -p artifacts
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.lint srtb_tpu/ \
  --format json > artifacts/lint.json \
  || { cat artifacts/lint.json; exit 1; }

echo "== [5/23] plan audit (compile-time HLO cards vs baseline) =="
# AOT-lowers every plan family and audits the compiled artifacts:
# spectrum-sized HBM sweeps vs the declared hbm_passes floor, donation
# proven aliased (not silently dropped), no f64/host-callback/
# collective creep.  Fails on any drift from
# srtb_tpu/analysis/plan_cards.json (accept intentional changes with
# --write-baseline + a note); the selftest then proves the gate still
# catches a dropped donation and an injected extra spectrum pass.
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.plan_audit \
  --out artifacts/plan_cards_audit.json
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.plan_audit --selftest

echo "== [6/23] pytest (8-device CPU mesh) =="
FAST_ARGS=()
if [ "${1:-}" = "--fast" ]; then
  # one source of truth for what "slow" means: the pytest marker
  # (registered in pyproject.toml), not a hardcoded deselect list
  FAST_ARGS=(-m "not slow")
fi
python -m pytest tests/ -q "${FAST_ARGS[@]}"

echo "== [7/23] bench smoke (with the roofline/audit cross-check) =="
JAX_PLATFORMS=cpu SRTB_BENCH_LOG2N=16 SRTB_BENCH_AUDIT=1 \
  python bench.py | tail -1

echo "== [8/23] fused-plan parity (spectrum-pass fusion, Pallas interpret on CPU) =="
JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.pipeline.segment import SegmentProcessor, waterfall_to_numpy

n = 1 << 16
base = dict(baseband_input_count=n, baseband_input_bits=2,
            baseband_format_type="simple", baseband_freq_low=1405.0,
            baseband_bandwidth=64.0, baseband_sample_rate=128e6, dm=30.0,
            spectrum_channel_count=8,
            mitigate_rfi_average_method_threshold=25.0,
            mitigate_rfi_spectral_kurtosis_threshold=1.05,
            signal_detect_signal_noise_threshold=5.0,
            signal_detect_max_boxcar_length=8,
            baseband_reserve_sample=False, fft_strategy="four_step")
raw = make_dispersed_baseband(n, 1405.0, 64.0, 30.0,
                              pulse_positions=n // 2, pulse_amp=30.0,
                              nbits=2)

legacy = SegmentProcessor(Config(fused_tail="off", **base))
fused = SegmentProcessor(Config(fused_tail="on", use_pallas=True,
                                use_pallas_sk=True, **base))
assert legacy.hbm_passes == 7 and fused.hbm_passes == 4, (
    legacy.hbm_passes, fused.hbm_passes)
assert fused._skzap and fused.plan_name.endswith("+ftail+skzap")
assert legacy.plan_signature() != fused.plan_signature()
wf_l, res_l = legacy.process(raw)
wf_f, res_f = fused.process(raw)
np.testing.assert_array_equal(np.asarray(res_l.signal_counts),
                              np.asarray(res_f.signal_counts))
np.testing.assert_array_equal(np.asarray(res_l.zero_count),
                              np.asarray(res_f.zero_count))
a, b = waterfall_to_numpy(wf_l), waterfall_to_numpy(wf_f)
scale = np.abs(a).max()
np.testing.assert_allclose(b, a, atol=1e-3 * scale, rtol=0)
print(f"fused-plan parity OK: plan {fused.plan_name} "
      f"(hbm_passes {fused.hbm_passes}) matches legacy 7-pass chain, "
      "detections bit-identical")

# ---- front-fused staged megakernel parity (ISSUE 15): staged_ffuse
# (raw bytes -> blocked intermediate -> dedispersed spectrum, declared
# hbm_passes 2) vs the staged+skzap plan it demotes onto (hbm 4) —
# decisions bit-identical under Pallas interpret.
import os
os.environ["SRTB_STAGED_ROWS_IMPL"] = "pallas2"
from srtb_tpu.io.synth import make_dispersed_baseband as _synth
raw2 = _synth(n, 1405.0, 64.0, 30.0, pulse_positions=n // 2,
              pulse_amp=8.0, nbits=2)
fbase = dict(base, fused_tail="on", use_pallas=True,
             use_pallas_sk=True,
             mitigate_rfi_spectral_kurtosis_threshold=5.0)
ffuse = SegmentProcessor(Config(front_fuse="on", **fbase), staged=True)
staged = SegmentProcessor(Config(front_fuse="off", **fbase),
                          staged=True)
assert ffuse.hbm_passes == 2 and staged.hbm_passes == 4, (
    ffuse.hbm_passes, staged.hbm_passes)
assert ffuse.front_fuse and "+ffuse" in ffuse.plan_name
assert ffuse.plan_signature() != staged.plan_signature()
wf_ff, res_ff = ffuse.process(raw2)
wf_st, res_st = staged.process(raw2)
np.testing.assert_array_equal(np.asarray(res_ff.signal_counts),
                              np.asarray(res_st.signal_counts))
np.testing.assert_array_equal(np.asarray(res_ff.zero_count),
                              np.asarray(res_st.zero_count))
a2, b2 = waterfall_to_numpy(wf_st), waterfall_to_numpy(wf_ff)
scale2 = np.abs(a2).max()
assert scale2 > 0
np.testing.assert_allclose(b2, a2, atol=1e-3 * scale2, rtol=0)
print(f"ffuse parity OK: plan {ffuse.plan_name} (hbm_passes "
      f"{ffuse.hbm_passes}) vs {staged.plan_name} (hbm_passes "
      f"{staged.hbm_passes}), decisions bit-identical")
EOF

echo "== [9/23] ring parity smoke (incremental H2D ring on vs off, Pallas interpret) =="
# The ISSUE-8 acceptance gate: ring-on output is bit-identical to
# ring-off on a Pallas-kernel plan (interpret mode on CPU), and the
# per-segment h2d_bytes counter equals the stride model exactly — the
# full segment on the one cold dispatch, stride_bytes (segment minus
# the reserved overlap tail) on every warm dispatch.  The plan-audit
# stage [5/20] already proved the carry donation is a real alias for
# every ring-v1 family; this proves the runtime keeps its half of the
# contract.
JAX_PLATFORMS=cpu python - <<'EOF'
import os, tempfile
import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.pipeline.runtime import Pipeline
from srtb_tpu.utils.metrics import metrics

tmp = tempfile.mkdtemp(prefix="srtb_ci_ring_")
n = 1 << 14
make_dispersed_baseband(n * 4, 1405.0, 64.0, 0.05, pulse_positions=n,
                        nbits=8).tofile(os.path.join(tmp, "bb.bin"))

class Cap:
    def __init__(self): self.out = []
    def push(self, w, p):
        d = w.detect
        self.out.append((np.asarray(d.signal_counts).copy(),
                         np.asarray(d.zero_count).copy(),
                         np.asarray(d.time_series).copy()))

def run(ring):
    metrics.reset()
    cfg = Config(baseband_input_count=n, baseband_input_bits=8,
                 baseband_freq_low=1405.0, baseband_bandwidth=64.0,
                 baseband_sample_rate=128e6, dm=0.05,
                 input_file_path=os.path.join(tmp, "bb.bin"),
                 baseband_output_file_prefix=os.path.join(tmp, ring + "_"),
                 spectrum_channel_count=64,
                 mitigate_rfi_average_method_threshold=100.0,
                 mitigate_rfi_spectral_kurtosis_threshold=2.0,
                 baseband_reserve_sample=True, writer_thread_count=0,
                 fft_strategy="four_step", use_pallas=True,
                 inflight_segments=3, ingest_ring=ring)
    sink = Cap()
    with Pipeline(cfg, sinks=[sink]) as pipe:
        stats = pipe.run()
    h2d, cold = metrics.get("h2d_bytes"), metrics.get("ring_cold_dispatches")
    metrics.reset()
    return stats, sink, h2d, cold, pipe.processor

s_on, c_on, h_on, cold_on, proc = run("on")
s_off, c_off, h_off, cold_off, _ = run("off")
assert proc.ring and proc.plan_name.endswith("+ring"), proc.plan_name
assert s_on.segments == s_off.segments >= 4
for a, b in zip(c_on.out, c_off.out):
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
seg_b, stride = proc._segment_bytes, proc.stride_bytes
assert h_on == seg_b + (s_on.segments - 1) * stride, (h_on, seg_b, stride)
assert h_off == s_off.segments * seg_b, h_off
assert cold_on == 1 and cold_off == 0, (cold_on, cold_off)
print(f"ring parity OK: plan {proc.plan_name}, {s_on.segments} segments "
      f"bit-identical; h2d ring-on {int(h_on)} B == cold {seg_b} + "
      f"{s_on.segments - 1} x stride {stride} (ring-off {int(h_off)} B; "
      f"saved {int(h_off - h_on)} B = reserved fraction "
      f"{proc.reserved_bytes / seg_b:.1%} per warm segment)")
EOF

echo "== [10/23] telemetry + sanitizer smoke (journal + report + /metrics + /healthz + Config.sanitize) =="
JAX_PLATFORMS=cpu python - <<'EOF'
import json, os, tempfile, urllib.request

from srtb_tpu.config import Config
from srtb_tpu.gui.server import WaterfallHTTPServer
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.pipeline.runtime import Pipeline
from srtb_tpu.tools import telemetry_report as TR

tmp = tempfile.mkdtemp(prefix="srtb_ci_tele_")
n = 1 << 16
make_dispersed_baseband(n * 3, 1405.0, 64.0, 0.0, pulse_positions=n,
                        nbits=8).tofile(os.path.join(tmp, "bb.bin"))
journal = os.path.join(tmp, "journal.jsonl")
cfg = Config(baseband_input_count=n, baseband_input_bits=8,
             baseband_freq_low=1405.0, baseband_bandwidth=64.0,
             baseband_sample_rate=128e6,
             input_file_path=os.path.join(tmp, "bb.bin"),
             baseband_output_file_prefix=os.path.join(tmp, "out_"),
             spectrum_channel_count=1 << 8,
             mitigate_rfi_average_method_threshold=100.0,
             mitigate_rfi_spectral_kurtosis_threshold=2.0,
             baseband_reserve_sample=False, writer_thread_count=0,
             inflight_segments=3,  # the async overlap engine
             telemetry_journal_path=journal)
with Pipeline(cfg, sinks=[]) as pipe:
    stats = pipe.run()
assert stats.segments >= 2, stats
# journal non-empty and parseable by telemetry_report
recs = TR.load(journal)
assert recs, "telemetry journal is empty"
# v8 span fields (async engine + resilience + perf observatory) on
# every record: device-time accounting + live roofline + compile/cache
# books must ride every span, not just /metrics
for rec in recs:
    assert rec["v"] == 11, rec
    assert "overlap_hidden_ms" in rec and rec["inflight_depth"] >= 1, rec
    for key in ("degrade_level", "retries", "requeues", "restarts",
                "device_ms", "achieved_msamps", "roofline_frac",
                "compile_ms", "plan_compiles", "aot_cache_hits",
                "aot_cache_misses"):
        assert key in rec, (key, rec)
    assert rec["device_ms"] > 0 and rec["roofline_frac"] > 0, rec
# the lazy-jit first dispatch was counted as the run's compile event
assert recs[-1]["plan_compiles"] >= 1 and recs[-1]["compile_ms"] > 0
rep = TR.report(journal)
for stage in ("ingest", "dispatch", "fetch", "sink", "overlap"):
    assert rep["stages"][stage]["count"] == stats.segments, (stage, rep)
assert rep["overlap"]["records"] == stats.segments, rep["overlap"]
assert TR.main([journal, "--format", "json"]) == 0
# live endpoints from a WaterfallHTTPServer
srv = WaterfallHTTPServer(tmp, port=0).start()
try:
    base = f"http://127.0.0.1:{srv.port}"
    prom = urllib.request.urlopen(base + "/metrics").read().decode()
    assert "# TYPE srtb_stage_seconds histogram" in prom, prom[:400]
    assert 'srtb_stage_seconds_bucket{le="+Inf",stage="dispatch"}' in prom
    assert 'srtb_stage_seconds_bucket{le="+Inf",stage="overlap"}' in prom
    assert "srtb_inflight_depth" in prom
    # perf-observatory families (ISSUE 14): live roofline gauges,
    # device-time histogram, compile/cache counters all scrapeable
    assert "# TYPE srtb_device_seconds histogram" in prom
    for fam in ("srtb_roofline_frac", "srtb_achieved_msamps",
                "srtb_achieved_gbps", "srtb_compile_seconds",
                "srtb_plan_compiles", "srtb_aot_cache_hits",
                "srtb_aot_cache_misses"):
        assert f"\n{fam} " in prom or prom.startswith(f"{fam} "), fam
    h = json.loads(urllib.request.urlopen(base + "/healthz").read())
    assert h["ok"] and h["status"] == "ok", h
finally:
    srv.stop()
print(f"telemetry smoke OK: {stats.segments} segments, "
      f"{len(recs)} v5 spans, overlap stage live, "
      "/metrics + /healthz live")

# one short pipeline with the runtime sanitizer armed: transfer
# tripwire + NaN tripwires + thread checks all live on a real run
import numpy as np
cfg_s = cfg.replace(sanitize=True, inflight_segments=2,
                    telemetry_journal_path="",
                    baseband_output_file_prefix=os.path.join(
                        tmp, "san_"))
with Pipeline(cfg_s, sinks=[]) as pipe:
    stats_s = pipe.run()
assert stats_s.segments == stats.segments, (stats_s, stats)
assert not hasattr(np.asarray, "_srtb_sanitize_orig"), \
    "sanitizer tripwire not restored"
print(f"sanitizer smoke OK: {stats_s.segments} segments with "
      "Config.sanitize on, tripwire restored")
EOF

echo "== [11/23] fault-injection smoke (one transient fault at every site -> recovery + v8 telemetry) =="
JAX_PLATFORMS=cpu python - <<'EOF'
import json, os, tempfile

import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.pipeline.runtime import Pipeline
from srtb_tpu.pipeline.segment import SegmentProcessor
from srtb_tpu.tools import telemetry_report as TR
from srtb_tpu.utils.metrics import metrics

tmp = tempfile.mkdtemp(prefix="srtb_ci_fault_")
n = 1 << 14
make_dispersed_baseband(n * 4, 1405.0, 64.0, 0.0, pulse_positions=n,
                        nbits=8).tofile(os.path.join(tmp, "bb.bin"))

def cfg(tag, **kw):
    return Config(baseband_input_count=n, baseband_input_bits=8,
                  baseband_freq_low=1405.0, baseband_bandwidth=64.0,
                  baseband_sample_rate=128e6,
                  input_file_path=os.path.join(tmp, "bb.bin"),
                  baseband_output_file_prefix=os.path.join(tmp, tag),
                  spectrum_channel_count=1 << 8,
                  mitigate_rfi_average_method_threshold=100.0,
                  mitigate_rfi_spectral_kurtosis_threshold=2.0,
                  baseband_reserve_sample=False, writer_thread_count=0,
                  inflight_segments=2, retry_backoff_base_s=0.001, **kw)

class Cap:
    def __init__(self): self.out = []
    def push(self, w, p):
        d = w.detect
        self.out.append((np.asarray(d.signal_counts).copy(),
                         np.asarray(d.zero_count).copy()))

proc = SegmentProcessor(cfg("p_"))
metrics.reset()
clean = Cap()
with Pipeline(cfg("clean_"), sinks=[clean], processor=proc) as pipe:
    st0 = pipe.run()

metrics.reset()
plan = ("ingest:raise@1,h2d:raise@1,dispatch:raise@2,fetch:raise@2,"
        "sink_write:raise@3,checkpoint:raise@3")
faulted = Cap()
journal = os.path.join(tmp, "faults.jsonl")
with Pipeline(cfg("fault_", fault_plan=plan,
                  checkpoint_path=os.path.join(tmp, "ck.json"),
                  telemetry_journal_path=journal),
              sinks=[faulted], processor=proc) as pipe:
    st1 = pipe.run()
    assert pipe.faults.unfired() == [], pipe.faults.unfired()

# recovery: same segment count, bit-identical detections, no loss
assert st1.segments == st0.segments, (st1, st0)
for (a, b), (c, d) in zip(clean.out, faulted.out):
    np.testing.assert_array_equal(a, c)
    np.testing.assert_array_equal(b, d)
assert metrics.get("retries_total") == 6, metrics.get("retries_total")
assert metrics.get("segments_dropped") == 0
prom = metrics.prometheus()
assert "srtb_retries_total 6" in prom, prom[:400]
assert "srtb_faults_injected 6" in prom
# v3 journal fields + report resilience section
recs = TR.load(journal)
assert recs and all(r["v"] == 11 for r in recs)
# the checkpoint-site retry of the last segment lands after that
# segment's journal write: the final record carries 5 of the 6
assert recs[-1]["retries"] == 5 and recs[-1]["requeues"] == 0
rep = TR.report(journal)
assert rep["resilience"]["retries"] == 5, rep["resilience"]
print(f"fault-injection smoke OK: {st1.segments} segments recovered "
      "bit-identical through 6 injected faults, retries accounted in "
      "/metrics + v8 journal")
EOF

echo "== [12/23] chaos smoke (self-healing compute: oom + compile_fail + device_halt in one run) =="
# The ISSUE-9 acceptance gate: a deterministic fault plan injecting all
# three device-fault classes completes with accounted-only loss,
# detection decisions identical to the clean run, and the
# plan_demotions / device_reinits counters matching the injected plan
# EXACTLY; a clean run with the ladder armed is bit-identical to one
# without it (zero-cost off).  The selftest then proves the gate
# catches an unhandled fault class (an injected fatal, and a device
# fault with self-healing disabled).
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.chaos_soak --segments 6 \
  --plan "dispatch:oom@1,fetch:compile_fail@3,h2d:device_halt@5" \
  | tail -1
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.chaos_soak --selftest

echo "== [13/23] crash-soak smoke (SIGKILL exactly-once: manifest recovery + fsck + bit-identical union) =="
# The ISSUE-10 acceptance gate, CI-sized: a deterministic two-kill plan
# — one SIGKILL mid-checkpoint-flush (between sink commit and the
# checkpoint update, the duplicate-on-resume window) and one mid-
# sink-rename (orphan temp + uncommitted intent) — then recovery to
# completion.  Gate: fsck exits clean, the final output set is
# bit-identical (paths + SHA-256) to an uninterrupted golden run, the
# replay-skip and rollback paths both provably fired.  The fsck
# selftest then proves the verifier catches a forged WAL CRC, a
# deleted committed artifact, bit rot and a checkpoint ahead of the
# manifest.
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.crash_soak --segments 5 \
  --kills 2 --kill-plan "ckpt_stall@1,rename@1" --log2n 13 | tail -1
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.fsck --selftest

echo "== [14/23] multichip dryrun (8 virtual devices) =="
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "== [15/23] fleet smoke (multi-tenant bulkheads: 3 streams, 1 victim, shared plan cache) =="
# The ISSUE-11 acceptance gate, CI-sized: 3 seeded streams on one
# device, a stream-selector fault plan injected into stream0 (oom ->
# victim-only demotion, plus a transient sink fault and a fetch
# stall).  Gate: every healthy stream's output set (paths + SHA-256)
# bit-identical to its solo single-stream golden run, the victim's
# loss accounted-only with demotions attributed to its stream id in
# the v8 journal, and the shared AOT plan cache recording exactly ONE
# compile for the shared plan family.  The selftest then proves the
# gate catches cross-stream leakage (an UNSCOPED fault plan arming in
# every lane must FAIL the healthy-journal attribution check).
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.fleet_soak --streams 3 \
  --segments 4 --log2n 12 | tail -1
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.fleet_soak --selftest

echo "== [16/23] fleet-batch smoke (cross-tenant continuous batching: 4 streams, one shared dispatch) =="
# The ISSUE-17 acceptance gate, CI-sized: the round-15 fleet soak
# re-run with the batch former armed (fleet_batch_max=4).  Gate, on
# top of the bulkhead checks above: the v10 journal records batched
# dispatches with batch_size-weighted accounting that matches the
# batched_dispatches/batched_segments counters, the implied device
# dispatch count is <= segments/2 (amortization actually happened),
# the shared plan family still compiles exactly ONCE, outputs match
# the solo goldens (decisions + .bin bitwise, float artifacts within
# the documented vmap tolerance), and the victim exits its batch
# group without retiring its neighbours' programs.
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.fleet_soak --streams 4 \
  --segments 5 --log2n 12 --batch 4 | tail -1

echo "== [17/23] race-soak smoke (seeded schedule perturbation + lockdep, Config.tsan) =="
# The ISSUE-18 acceptance gate, CI-sized.  First the selftest: the
# lockdep layer must TRAP a deliberately injected lock-order inversion
# (and stay quiet on a consistent global order) — a soak that cannot
# catch a planted bug gates nothing.  Then the short deterministic
# soak: 2 streams, batch former armed, one injected fetch stall on the
# victim, with the SchedulePerturber injecting seeded sleeps at every
# instrumented lock acquisition (Config.tsan=1 on the fleet lanes
# only; the solo goldens stay canonical).  Gate: every fleet_soak
# invariant holds under perturbation (bit-identical healthy outputs /
# vmap tolerance, accounted-only victim loss, one shared compile), no
# deadlock within the deadline (on expiry: every live thread's stack
# + creation site), the perturbation journal replays exactly against
# a fresh perturber with the same seed, and no TsanError (order
# cycle / ownership violation) escaped the run.
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.race_soak --selftest
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.race_soak --streams 2 \
  --segments 4 --log2n 12 --batch 2 --seed 0 --deadline 240 | tail -1

echo "== [18/23] archive-replay smoke (full-throughput replay: SIGTERM resume + bit-identical union + micro-batch tolerance) =="
# The ISSUE-12 acceptance gate, CI-sized: a 2-file fleet-fanned replay
# (deterministic timestamps, per-file checkpoint + manifest namespaces)
# killed by a SIGTERM steered into one lane's sink-write window, then
# resumed to completion.  Gate: fsck-clean manifests, no orphan temps,
# the final output set (paths + SHA-256) BIT-IDENTICAL to per-file
# streamed golden runs, and the micro-batched throughput mode
# reproducing identical decisions (same artifact set, raw dumps
# bitwise, float artifacts within the documented vmap tolerance).
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.archive_replay --selftest \
  --segments 4 --log2n 13 | tail -1

echo "== [19/23] trace/incident smoke (causal tracing + flight recorder + bundle + Chrome-trace export) =="
# The ISSUE-13 acceptance gate, CI-sized: a clean traced run proves
# every segment leaves a complete ingest->dispatch->fetch->sink causal
# chain whose export is valid Chrome-trace JSON (schema-checked, flow
# arrows crossing the engine/sink thread boundary — no Perfetto needed
# in CI); then a seeded fault-plan escalation (oom -> one demotion ->
# ladder exhausted) must produce EXACTLY ONE incident bundle whose
# events hold the injected fault site, the device classification, the
# heal decision, the manifest disposition, and the offending trace_id.
JAX_PLATFORMS=cpu python - <<'EOF'
import json, os, tempfile

from srtb_tpu.config import Config
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.pipeline.runtime import Pipeline
from srtb_tpu.tools import trace_export as TE
from srtb_tpu.utils import events

tmp = tempfile.mkdtemp(prefix="srtb_ci_trace_")
n = 1 << 14
make_dispersed_baseband(n * 4, 1405.0, 64.0, 0.0, pulse_positions=n // 2,
                        pulse_amp=30.0, nbits=8).tofile(
    os.path.join(tmp, "bb.bin"))

def cfg(tag, **kw):
    return Config(baseband_input_count=n, baseband_input_bits=8,
                  baseband_freq_low=1405.0, baseband_bandwidth=64.0,
                  baseband_sample_rate=128e6,
                  input_file_path=os.path.join(tmp, "bb.bin"),
                  baseband_output_file_prefix=os.path.join(tmp, tag),
                  spectrum_channel_count=1 << 6,
                  mitigate_rfi_average_method_threshold=100.0,
                  mitigate_rfi_spectral_kurtosis_threshold=2.0,
                  baseband_reserve_sample=False, writer_thread_count=0,
                  retry_backoff_base_s=0.001, **kw)

# leg 1: clean traced run -> valid Chrome-trace export with flows
dump = os.path.join(tmp, "events.jsonl")
with Pipeline(cfg("clean_", inflight_segments=3,
                  events_dump_path=dump), sinks=[]) as pipe:
    stats = pipe.run()
doc = TE.render(TE.load_events(dump))
problems = TE.validate(doc)
assert not problems, problems
slices = [e for e in doc["traceEvents"] if e["ph"] == "X"]
for stage in ("ingest", "dispatch", "fetch", "sink"):
    assert sum(1 for e in slices if e["name"] == stage) == stats.segments
starts = [e for e in doc["traceEvents"] if e["ph"] == "s"]
finishes = {e["id"]: e for e in doc["traceEvents"] if e["ph"] == "f"}
assert len(starts) == len(finishes) == stats.segments
assert all(s["tid"] != finishes[s["id"]]["tid"] for s in starts), \
    "flow must cross the engine->sink thread boundary"
assert TE.main([dump, "--validate"]) == 0

# leg 2: seeded escalation -> exactly one bundle, offending trace inside
from srtb_tpu.resilience.errors import LadderExhausted
inc = os.path.join(tmp, "incidents")
try:
    with Pipeline(cfg("esc_", inflight_segments=1,
                      fault_plan="dispatch:oom@1,fetch:oom@2",
                      plan_ladder="staged", device_reinit_max=0,
                      incident_dir=inc,
                      checkpoint_path=os.path.join(tmp, "ck.json"),
                      run_manifest_path=os.path.join(tmp, "m.wal"))) as pipe:
        pipe.run()
    raise AssertionError("escalation did not escalate")
except LadderExhausted:
    pass
bundles = [d for d in os.listdir(inc) if d.startswith("incident_")]
assert len(bundles) == 1, bundles
b = os.path.join(inc, bundles[0])
meta = json.load(open(os.path.join(b, "incident.json")))
assert meta["kind"] == "ladder_exhausted" and meta["trace_id"] > 0
evs = [json.loads(ln) for ln in open(os.path.join(b, "events.jsonl"))]
types = [e["type"] for e in evs]
assert types.count("fault.injected") == 2 and "heal.demote" in types
assert types.count("fault.device") == 2 and "manifest.ckpt" in types
tr = [json.loads(ln) for ln in open(os.path.join(b, "trace.jsonl"))]
assert tr and all(e["trace"] == meta["trace_id"] for e in tr)
# the bundle's recorder tail exports as valid Chrome-trace JSON too
assert TE.main([b, "--validate"]) == 0
print(f"trace/incident smoke OK: {stats.segments} traced segments "
      f"exported with {len(starts)} cross-thread flows; escalation "
      f"produced exactly one bundle ({bundles[0]}) carrying trace "
      f"{meta['trace_id']}")
EOF

echo "== [20/23] canary + quality smoke (pulse-injection sensitivity gate + quality report artifact) =="
# The ISSUE-16 acceptance gate, CI-sized.  Leg 1 (clean): a file-mode
# run with the canary on and the quality epilogue enabled must inject,
# recover, and PASS every sensitivity check (auto-calibrated expected
# S/N), journal v9 quality + canary extras, and keep the science
# outputs silent (canary segments quarantined).  Leg 2 (degraded): the
# same run with 61/64 channels zapped and the clean run's measured S/N
# pinned as the expectation must FAIL the sensitivity check, degrade
# detection health, and drop an incident bundle carrying the canary
# verdict + quality timeline.  The quality report renders both runs
# into the CI artifact set.
JAX_PLATFORMS=cpu python - <<'EOF'
import json, os, tempfile

import numpy as np

from srtb_tpu.config import Config
from srtb_tpu.pipeline.runtime import Pipeline
from srtb_tpu.utils import telemetry
from srtb_tpu.utils.metrics import metrics

tmp = tempfile.mkdtemp(prefix="srtb_ci_canary_")
n, segments = 1 << 14, 4
rng = np.random.default_rng(7)
rng.normal(128, 8, n * segments).clip(0, 255).astype("uint8").tofile(
    os.path.join(tmp, "noise.bin"))

def cfg(tag, **kw):
    return Config(baseband_input_count=n, baseband_input_bits=8,
                  baseband_freq_low=1405.0, baseband_bandwidth=64.0,
                  baseband_sample_rate=128e6, dm=0.0,
                  input_file_path=os.path.join(tmp, "noise.bin"),
                  baseband_output_file_prefix=os.path.join(tmp, tag),
                  spectrum_channel_count=1 << 6,
                  mitigate_rfi_average_method_threshold=100.0,
                  mitigate_rfi_spectral_kurtosis_threshold=2.0,
                  baseband_reserve_sample=False, writer_thread_count=0,
                  retry_backoff_base_s=0.001, inflight_segments=3,
                  quality_stats=True, canary_every_segments=2,
                  stream_name="ci",
                  telemetry_journal_path=os.path.join(
                      tmp, f"{tag}.jsonl"), **kw)

# leg 1: clean run -> every canary recovered, science outputs silent
with Pipeline(cfg("clean"), sinks=[]) as pipe:
    stats = pipe.run()
assert stats.segments == segments and stats.signals == 0
checked = metrics.get("canary_checked")
failed = metrics.get("canary_failed")
expected = metrics.get("canary_last_snr")
assert checked == 2 and failed == 0, (checked, failed)
assert expected > 5.0, expected
spans = [json.loads(ln) for ln in open(os.path.join(tmp, "clean.jsonl"))
         if ln.strip().startswith("{")]
spans = [r for r in spans if r.get("type") == "segment_span"]
assert all(r["v"] == 11 and "quality" in r for r in spans)
assert sum(1 for r in spans if "canary" in r) == 2
metrics.reset()

# leg 2: zap 61/64 channels out from under the pulse -> gate FAILS
inc = os.path.join(tmp, "incidents")
with Pipeline(cfg("deg", mitigate_rfi_freq_list="1405-1466",
                  canary_expected_snr=expected, incident_dir=inc,
                  incident_min_interval_s=0.0), sinks=[]) as pipe:
    pipe.run()
assert metrics.get("canary_failed") >= 1
assert metrics.get("detection_health_state") == 1
health = telemetry.health()
assert health["detection"]["state"] == "degraded"
bundles = [d for d in os.listdir(inc) if "canary_sensitivity" in d]
assert bundles, os.listdir(inc)
extra = json.load(open(os.path.join(inc, bundles[0], "extra.json")))
assert extra["canary"]["ok"] is False and extra["quality_timeline"]
with open("artifacts/canary_journal_path.txt", "w") as fh:
    fh.write(os.path.join(tmp, "clean.jsonl"))
print(f"canary smoke OK: clean run recovered S/N {expected:.2f} "
      f"({checked} checks, quarantined); degraded run failed the "
      f"sensitivity gate and produced {bundles[0]}")
EOF
# the science-observatory artifact: render the clean leg's journal
CANARY_JOURNAL=$(cat artifacts/canary_journal_path.txt)
python -m srtb_tpu.tools.quality_report "$CANARY_JOURNAL" \
  --format json > artifacts/quality_report.json
python -m srtb_tpu.tools.quality_report "$CANARY_JOURNAL" \
  > artifacts/quality_report.md
grep -q '"canary"' artifacts/quality_report.json
grep -q '## Canary' artifacts/quality_report.md

echo "== [21/23] perf-gate smoke (noise-aware regression gate + ledger trajectory) =="
# The ISSUE-14 acceptance gate: (a) the gate's selftest proves an
# injected dispatch-path slowdown (Config.fault_plan stall) FAILS the
# statistical gate while a clean rerun passes within the COMPUTED
# noise floor; (b) a calibrated mini-bench is compared against the
# checked-in CPU baseline (PERF_BASELINE.json) — cross-host runs are
# rescaled by the calibration workload and gated at a generous
# smoke-alarm effect floor, so CI catches a gross regression without
# flaking on scheduler noise; (c) the legacy BENCH_r0*.json history
# imports into a perf ledger idempotently and perf_report renders the
# trajectory.
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.perf_gate --selftest | tail -1
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.perf_gate \
  --baseline PERF_BASELINE.json --min-effect 0.5 \
  --ledger artifacts/perf_ledger.jsonl | tail -1
python -m srtb_tpu.tools.perf_ledger artifacts/perf_ledger.jsonl \
  --import 'BENCH_r0*.json'
# idempotent: a second import must skip everything it already ingested
python -m srtb_tpu.tools.perf_ledger artifacts/perf_ledger.jsonl \
  --import 'BENCH_r0*.json' | grep -q '"imported": 0'
python -m srtb_tpu.tools.perf_report artifacts/perf_ledger.jsonl \
  --format json > artifacts/perf_trajectory.json
python - <<'EOF'
import json
doc = json.load(open("artifacts/perf_trajectory.json"))
assert doc["records"] >= 5, doc["records"]
rows = [r for g in doc["groups"].values() for r in g["rows"]]
assert any(r["source"] == "import" for r in rows)
assert any(r["source"] == "gate" for r in rows)
print(f"perf trajectory OK: {doc['records']} records across "
      f"{len(doc['groups'])} group(s), imports + gate captures present")
EOF

echo "== [22/23] migration smoke (elastic pool: scoped device kill + rolling restart, live migration bit-identical) =="
# The ISSUE-19 acceptance gate, CI-sized: 3 seeded streams placed
# across a 2-member VIRTUAL pool (distinct plan caches / halt domains
# on one CPU device).  Kill mode: a scheduled mid-run halt of member
# dev1 — its lanes drain-migrate onto the survivor instead of a
# fleet-wide reinit.  Gate: every victim resumes on the peer with its
# output set (paths + SHA-256) and decision taps bit-identical to the
# solo goldens, loss accounted-only, exactly ONE extra cold ring
# dispatch per migrated lane (ring_cold == streams + migrations),
# pool compiles == pool size (the migrant rejoins the survivor's
# family at rung 0 — zero healthy-lane demotions/recompiles),
# device_reinits == 0, and the v11 journals stamp every span with its
# device (victims end on a different member than they started).
# Rolling mode then drains BOTH members one at a time (the operator
# path), pacing each drain on the previous migrants' resumption.
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.fleet_soak --migrate \
  --streams 3 --segments 6 --log2n 12 --kill-device 1 --kill-at 2 \
  | tail -1
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.fleet_soak --migrate \
  --rolling --streams 3 --segments 6 --log2n 12 --kill-at 2 | tail -1

echo "== [23/23] fleet control tower (aggregator + rollup store + cross-device trace join + console + regression watch) =="
# The ISSUE-20 acceptance gate, CI-sized: re-run the 2-member virtual
# pool migration soak, then drive its three v11 journals + the flight
# recorder dump through the REAL tower path: aggregator -> rollup
# store (compaction byte-idempotent, cursor resume reads zero) ->
# cross-device Perfetto join (a migrated stream's lane flows span
# BOTH device process-tracks, same validate() gate as trace_export)
# -> /fleet endpoint + pool-aggregated /metrics + operator console.
JAX_PLATFORMS=cpu python - <<'EOF'
import glob, json, os, shutil, sys, urllib.request

OUT = "artifacts/obs"
shutil.rmtree(OUT, ignore_errors=True)
os.makedirs(OUT, exist_ok=True)

from srtb_tpu.tools.fleet_soak import run_migrate
os.makedirs(os.path.join(OUT, "migrate_run"), exist_ok=True)
rep = run_migrate(streams=3, segments=6, log2n=12, kill_device=1,
                  kill_at=2, tmpdir=os.path.join(OUT, "migrate_run"))
print("soak:", json.dumps({k: rep[k] for k in ("migrations", "device_drains")
                           if k in rep}))

from srtb_tpu.utils import events
ev_path = os.path.join(OUT, "events.jsonl")
n_ev = events.hub.dump_jsonl(ev_path)
assert n_ev > 0, "event dump empty"

journals = sorted(glob.glob(os.path.join(OUT, "migrate_run", "journal_*.jsonl")))
assert len(journals) == 3, journals

from srtb_tpu.obs.rollup import Aggregator
from srtb_tpu.obs.store import RollupStore
store_dir = os.path.join(OUT, "store")
store = RollupStore(store_dir)
agg = Aggregator(store, journals=journals, events_dumps=[ev_path])
got = agg.poll()
assert got["spans"] >= 18, got   # 3 streams x 6 segments
assert got["events"] > 0, got
agg.flush()
# idempotent compaction: byte-identical on re-run
store.compact()
def seg_bytes():
    return {n: open(os.path.join(store.segment_dir, n), "rb").read()
            for n in sorted(os.listdir(store.segment_dir))}
b1 = seg_bytes(); store.compact(); b2 = seg_bytes()
assert b1 == b2, "compaction not idempotent"
# resume cursor: a fresh aggregator re-reads nothing
agg2 = Aggregator(RollupStore(store_dir), journals=journals)
assert agg2.poll()["spans"] == 0, "cursor resume double-counted spans"
print(f"store OK: {got['spans']} spans, {got['events']} fleet events, "
      f"compaction idempotent, cursor resume clean")

from srtb_tpu.obs import trace_join
from srtb_tpu.tools.trace_export import validate
doc = trace_join.join([ev_path], journals)
problems = validate(doc)
assert not problems, problems
sd = doc["otherData"]["stream_devices"]
assert any(len(v) >= 2 for v in sd.values()), sd
with open(os.path.join(OUT, "fleet_trace.json"), "w") as f:
    json.dump(doc, f)
print(f"fleet trace OK: {len(doc['traceEvents'])} events, "
      f"stream_devices={json.dumps(sd)}")

from srtb_tpu.gui.server import WaterfallHTTPServer
srv = WaterfallHTTPServer(OUT, port=0, fleet_store_dir=store_dir).start()
try:
    base = f"http://127.0.0.1:{srv.port}"
    with urllib.request.urlopen(base + "/fleet", timeout=10) as r:
        fleet = json.loads(r.read().decode())
    assert fleet["devices"], fleet
    assert fleet["pool"]["migrations"] >= 1, fleet["pool"]
    assert fleet.get("store", {}).get("timeline"), "no migration timeline"
    with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
        prom = r.read().decode()
    assert "srtb_migrations_pool_sum" in prom, "pool aggregate family missing"
    assert "srtb_fleet_device_state_pool_max" in prom
    from srtb_tpu.tools import console
    assert console.main(["--url", base, "--once"]) == 0
finally:
    srv.stop()
print("console + /fleet + pool-aggregated /metrics OK")
EOF
# Mid-run regression watch selftest: mini pipeline -> journal ->
# aggregator rollup -> ledger history -> perf_stats verdict.  The
# injected dispatch stall must escalate EXACTLY one incident bundle
# (and latch on the second tick); the clean leg exactly zero.
JAX_PLATFORMS=cpu python -m srtb_tpu.obs.regression --selftest \
  2>/dev/null | tail -1 | tee artifacts/obs/regression_selftest.json
grep -q '"selftest": "ok"' artifacts/obs/regression_selftest.json

echo "CI OK"
