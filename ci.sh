#!/bin/bash
# One-command reproduction of the verification this repo is judged by
# (L8 parity with the reference's CircleCI matrix,
# ref: /root/reference/.circleci/config.yml — there: 2 toolchains x 2
# arches of the SYCL build + ctest; here: native build + static checks +
# the full pytest suite on the virtual 8-device CPU mesh + the bench and
# multichip dryrun smoke).
#
# Usage: ./ci.sh [--fast]   (--fast skips the slowest pytest cases)
#        ./ci.sh --hardware (arm the TPU watcher: probes the tunnel and
#                            fires the hardware queue on recovery — the
#                            repo-tracked re-arm path, round-3 verdict)
set -euo pipefail
cd "$(dirname "$0")"

if [ "${1:-}" = "--hardware" ]; then
  [ -f tools_tpu_watcher.sh ] || { echo "tools_tpu_watcher.sh missing" >&2; exit 1; }
  if [ -f /tmp/tpu_watcher.pid ] && kill -0 "$(cat /tmp/tpu_watcher.pid)" 2>/dev/null; then
    echo "TPU watcher already running (pid $(cat /tmp/tpu_watcher.pid))"
    exit 0
  fi
  nohup bash tools_tpu_watcher.sh >/dev/null 2>&1 &
  echo "TPU watcher armed (pid $!, log ${SRTB_WATCH_LOG:-/tmp/tpu_watcher.log})"
  exit 0
fi

echo "== [1/6] native build =="
make -C srtb_tpu/native

echo "== [2/6] native sanitizer harness (ASan/UBSan) =="
make -C srtb_tpu/native check

echo "== [3/6] static checks (compile + import) =="
python -m compileall -q srtb_tpu tests bench.py __graft_entry__.py
python - <<'EOF'
import importlib, pkgutil
import srtb_tpu
bad = []
for m in pkgutil.walk_packages(srtb_tpu.__path__, "srtb_tpu."):
    try:
        importlib.import_module(m.name)
    except Exception as e:  # noqa: BLE001 - report every import failure
        bad.append((m.name, e))
assert not bad, bad
print(f"all srtb_tpu modules import cleanly")
EOF

echo "== [4/6] pytest (8-device CPU mesh) =="
FAST_ARGS=()
if [ "${1:-}" = "--fast" ]; then
  FAST_ARGS=(--deselect tests/test_dist_fft.py::test_dist_fft_large_n_twiddle_precision
             --deselect tests/test_dist_fft.py::test_dist_rfft_large_n_twiddle_precision)
fi
python -m pytest tests/ -q "${FAST_ARGS[@]}"

echo "== [5/6] bench smoke =="
JAX_PLATFORMS=cpu SRTB_BENCH_LOG2N=16 python bench.py | tail -1

echo "== [6/6] multichip dryrun (8 virtual devices) =="
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "CI OK"
