#!/bin/bash
# One-command reproduction of the verification this repo is judged by
# (L8 parity with the reference's CircleCI matrix,
# ref: /root/reference/.circleci/config.yml — there: 2 toolchains x 2
# arches of the SYCL build + ctest; here: native build + static checks +
# the full pytest suite on the virtual 8-device CPU mesh + the bench and
# multichip dryrun smoke).
#
# Usage: ./ci.sh [--fast]   (--fast skips the slowest pytest cases)
#        ./ci.sh --hardware (arm the TPU watcher: probes the tunnel and
#                            fires the hardware queue on recovery — the
#                            repo-tracked re-arm path, round-3 verdict)
set -euo pipefail
cd "$(dirname "$0")"

if [ "${1:-}" = "--hardware" ]; then
  [ -f tools_tpu_watcher.sh ] || { echo "tools_tpu_watcher.sh missing" >&2; exit 1; }
  if [ -f /tmp/tpu_watcher.pid ] && kill -0 "$(cat /tmp/tpu_watcher.pid)" 2>/dev/null; then
    echo "TPU watcher already running (pid $(cat /tmp/tpu_watcher.pid))"
    exit 0
  fi
  nohup bash tools_tpu_watcher.sh >/dev/null 2>&1 &
  echo "TPU watcher armed (pid $!, log ${SRTB_WATCH_LOG:-/tmp/tpu_watcher.log})"
  exit 0
fi

echo "== [1/8] native build =="
make -C srtb_tpu/native

echo "== [2/8] native sanitizer harness (ASan/UBSan) =="
make -C srtb_tpu/native check

echo "== [3/8] static checks (compile + import) =="
python -m compileall -q srtb_tpu tests bench.py __graft_entry__.py
python - <<'EOF'
import importlib, pkgutil
import srtb_tpu
bad = []
for m in pkgutil.walk_packages(srtb_tpu.__path__, "srtb_tpu."):
    try:
        importlib.import_module(m.name)
    except Exception as e:  # noqa: BLE001 - report every import failure
        bad.append((m.name, e))
assert not bad, bad
print(f"all srtb_tpu modules import cleanly")
EOF

echo "== [4/8] srtb-lint (static analysis vs baseline) =="
# fails on findings not in srtb_tpu/analysis/baseline.json; accept an
# intentional finding with --write-baseline + a note, or a pragma
JAX_PLATFORMS=cpu python -m srtb_tpu.tools.lint srtb_tpu/

echo "== [5/8] pytest (8-device CPU mesh) =="
FAST_ARGS=()
if [ "${1:-}" = "--fast" ]; then
  # one source of truth for what "slow" means: the pytest marker
  # (registered in pyproject.toml), not a hardcoded deselect list
  FAST_ARGS=(-m "not slow")
fi
python -m pytest tests/ -q "${FAST_ARGS[@]}"

echo "== [6/8] bench smoke =="
JAX_PLATFORMS=cpu SRTB_BENCH_LOG2N=16 python bench.py | tail -1

echo "== [7/8] telemetry + sanitizer smoke (journal + report + /metrics + /healthz + Config.sanitize) =="
JAX_PLATFORMS=cpu python - <<'EOF'
import json, os, tempfile, urllib.request

from srtb_tpu.config import Config
from srtb_tpu.gui.server import WaterfallHTTPServer
from srtb_tpu.io.synth import make_dispersed_baseband
from srtb_tpu.pipeline.runtime import Pipeline
from srtb_tpu.tools import telemetry_report as TR

tmp = tempfile.mkdtemp(prefix="srtb_ci_tele_")
n = 1 << 16
make_dispersed_baseband(n * 3, 1405.0, 64.0, 0.0, pulse_positions=n,
                        nbits=8).tofile(os.path.join(tmp, "bb.bin"))
journal = os.path.join(tmp, "journal.jsonl")
cfg = Config(baseband_input_count=n, baseband_input_bits=8,
             baseband_freq_low=1405.0, baseband_bandwidth=64.0,
             baseband_sample_rate=128e6,
             input_file_path=os.path.join(tmp, "bb.bin"),
             baseband_output_file_prefix=os.path.join(tmp, "out_"),
             spectrum_channel_count=1 << 8,
             mitigate_rfi_average_method_threshold=100.0,
             mitigate_rfi_spectral_kurtosis_threshold=2.0,
             baseband_reserve_sample=False, writer_thread_count=0,
             inflight_segments=3,  # the async overlap engine
             telemetry_journal_path=journal)
with Pipeline(cfg, sinks=[]) as pipe:
    stats = pipe.run()
assert stats.segments >= 2, stats
# journal non-empty and parseable by telemetry_report
recs = TR.load(journal)
assert recs, "telemetry journal is empty"
# schema-v2 span fields (async engine) parse on every record
for rec in recs:
    assert rec["v"] == 2, rec
    assert "overlap_hidden_ms" in rec and rec["inflight_depth"] >= 1, rec
rep = TR.report(journal)
for stage in ("ingest", "dispatch", "fetch", "sink", "overlap"):
    assert rep["stages"][stage]["count"] == stats.segments, (stage, rep)
assert rep["overlap"]["records"] == stats.segments, rep["overlap"]
assert TR.main([journal, "--format", "json"]) == 0
# live endpoints from a WaterfallHTTPServer
srv = WaterfallHTTPServer(tmp, port=0).start()
try:
    base = f"http://127.0.0.1:{srv.port}"
    prom = urllib.request.urlopen(base + "/metrics").read().decode()
    assert "# TYPE srtb_stage_seconds histogram" in prom, prom[:400]
    assert 'srtb_stage_seconds_bucket{le="+Inf",stage="dispatch"}' in prom
    assert 'srtb_stage_seconds_bucket{le="+Inf",stage="overlap"}' in prom
    assert "srtb_inflight_depth" in prom
    h = json.loads(urllib.request.urlopen(base + "/healthz").read())
    assert h["ok"] and h["status"] == "ok", h
finally:
    srv.stop()
print(f"telemetry smoke OK: {stats.segments} segments, "
      f"{len(recs)} v2 spans, overlap stage live, "
      "/metrics + /healthz live")

# one short pipeline with the runtime sanitizer armed: transfer
# tripwire + NaN tripwires + thread checks all live on a real run
import numpy as np
cfg_s = cfg.replace(sanitize=True, inflight_segments=2,
                    telemetry_journal_path="",
                    baseband_output_file_prefix=os.path.join(
                        tmp, "san_"))
with Pipeline(cfg_s, sinks=[]) as pipe:
    stats_s = pipe.run()
assert stats_s.segments == stats.segments, (stats_s, stats)
assert not hasattr(np.asarray, "_srtb_sanitize_orig"), \
    "sanitizer tripwire not restored"
print(f"sanitizer smoke OK: {stats_s.segments} segments with "
      "Config.sanitize on, tripwire restored")
EOF

echo "== [8/8] multichip dryrun (8 virtual devices) =="
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "CI OK"
