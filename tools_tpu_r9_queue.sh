#!/bin/bash
# Round-9 TPU hardware backlog: the front-fused staged megakernel
# (staged_ffuse, ISSUE 15) — fold unpack -> forward-FFT pass 1 into
# the pallas2 row-FFT kernel and the Hermitian + RFI-s1 + chirp tail
# into pass 2's epilogue, staged hbm_passes 4 -> 2.  These legs are
# BOTH the A/B measurement and the Mosaic acceptance probe the
# FFUSE_MOSAIC_OK flag (ops/pallas_fft2.py) waits on: front_fuse=on
# forces the kernels, and a Mosaic rejection demotes down the audited
# ladder onto today's staged plan (the run still lands a row — check
# the row's "front_fuse"/"plan" fields to see which plan actually
# measured; plan=...+ffuse means Mosaic ACCEPTED, flip the flag).
# On top of the still-undrained r8 backlog.  Safe to re-run; each
# block is independent.  Run from the repo root with the TPU visible
# (tools_tpu_watcher.sh fires it automatically).
#
#   bash tools_tpu_r9_queue.sh [quick]
#
# "quick" drains only the new r9 rows (skips the r8 backlog and the
# long 2^30 blocks).
set -u
OUT=${SRTB_PERF_OUT:-PERF_TPU.jsonl}
stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }
note() { echo "{\"ts\": \"$(stamp)\", \"variant\": \"note\", \"note\": \"$1\"}" >> "$OUT"; }
run() {
  local tag="$1"; shift
  echo "== $tag =="
  local line
  line=$("$@" 2>/dev/null | grep '^{' | tail -1)
  if [ -n "$line" ]; then
    echo "{\"ts\": \"$(stamp)\", \"variant\": \"$tag\", \"result\": $line}" >> "$OUT"
    echo "$line"
  else
    echo "{\"ts\": \"$(stamp)\", \"variant\": \"$tag\", \"error\": true}" >> "$OUT"
  fi
}

QUICK=${1:-}

# ---- 0. the r8 backlog first (archive + periodicity legs) ----
if [ "$QUICK" != "quick" ] && [ -f tools_tpu_r8_queue.sh ]; then
  note "r9 queue: draining r8 backlog first"
  bash tools_tpu_r8_queue.sh quick
fi

note "r9 queue start: front-fused staged megakernel (staged_ffuse) A/B + Mosaic probe"

# ---- 1. kernel-level probe rows: fused unpack+pass1 vs the separate
#          unpack-then-pass1 chain (real Mosaic — the FFUSE_MOSAIC_OK
#          acceptance evidence), plus the rest of the kernel zoo for
#          context.  An error row here = Mosaic balked; keep the flag
#          False and file the rejection text.
run ffuse_kernels_27 env SRTB_BENCH_DEADLINE=900 \
    python -m srtb_tpu.tools.kernel_bench --log2n 27 --reps 5

# ---- 2. staged_ffuse A/B at 2^27 (forced staged so both legs run the
#          three-program chain; pallas2 rows are the ffuse
#          prerequisite and the off-leg's measured baseline).
run staged_ffuse_off_27 env SRTB_BENCH_LOG2N=27 SRTB_BENCH_STAGED=1 \
    SRTB_BENCH_FFT_STRATEGY=four_step SRTB_STAGED_ROWS_IMPL=pallas2 \
    SRTB_BENCH_FRONT_FUSE=off SRTB_BENCH_DEADLINE=1200 python bench.py
run staged_ffuse_on_27 env SRTB_BENCH_LOG2N=27 SRTB_BENCH_STAGED=1 \
    SRTB_BENCH_FFT_STRATEGY=four_step SRTB_STAGED_ROWS_IMPL=pallas2 \
    SRTB_BENCH_FRONT_FUSE=on SRTB_BENCH_DEADLINE=1200 python bench.py

# ---- 3. ffuse + ring at 2^27 (the carry alias surviving the fusion,
#          measured: warm stride uploads + the 2-sweep front together)
run staged_ffuse_ring_27 env SRTB_BENCH_LOG2N=27 SRTB_BENCH_STAGED=1 \
    SRTB_BENCH_FFT_STRATEGY=four_step SRTB_STAGED_ROWS_IMPL=pallas2 \
    SRTB_BENCH_FRONT_FUSE=on SRTB_BENCH_RING=on \
    SRTB_BENCH_DEADLINE=1200 python bench.py

if [ "$QUICK" = "quick" ]; then exit 0; fi

# ---- 4. the production staged shape, 2^30: the target this fusion
#          exists for (393 Msamp/s at round 2 — the front half's
#          un-fused passes are the largest single-plan traffic block
#          left on the board).
#          (fused_tail forced on: the ffuse epilogue IS the fused
#          tail, and "auto" gates bankless df64 fusion above 2^27 —
#          the r6 staged_fused_on_30 override, same reasoning)
run staged_ffuse_off_30 env SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 \
    SRTB_BENCH_STAGED=1 SRTB_BENCH_FFT_STRATEGY=four_step \
    SRTB_STAGED_ROWS_IMPL=pallas2 SRTB_BENCH_FUSED_TAIL=on \
    SRTB_BENCH_FRONT_FUSE=off \
    SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=2700 python bench.py
#          (SRTB_PALLAS2_VMEM_MB=112: the fused footprint models say
#          the 2^30 floor blocks need ~82-94 MiB — over the default
#          80 MiB budget but inside v5e's 128 MiB physical; give the
#          probe the headroom rather than measuring a guaranteed
#          vmem_limit rejection)
run staged_ffuse_on_30 env SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 \
    SRTB_BENCH_STAGED=1 SRTB_BENCH_FFT_STRATEGY=four_step \
    SRTB_STAGED_ROWS_IMPL=pallas2 SRTB_BENCH_FUSED_TAIL=on \
    SRTB_BENCH_FRONT_FUSE=on SRTB_PALLAS2_VMEM_MB=112 \
    SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=2700 python bench.py

note "r9 queue done"
