#!/bin/bash
# Round-6 TPU hardware backlog: spectrum-pass fusion A/Bs on top of the
# still-undrained r5 backlog.  The tunnel has been down since ~17:10Z
# Jul 30 (rounds 3-6); this queue first drains the r5 blocks (pallas2
# acceptance, anchored chirp, overlap, AOT cold/warm), then measures
# the round-6 fused plans the moment hardware returns.  Safe to re-run;
# each block is independent.  Run from the repo root with the TPU
# visible (tools_tpu_watcher.sh fires it automatically).
#
#   bash tools_tpu_r6_queue.sh [quick]
#
# "quick" drains only the new fused-plan rows (skips the r5 backlog and
# the long 2^30 blocks).
set -u
OUT=${SRTB_PERF_OUT:-PERF_TPU.jsonl}
stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }
note() { echo "{\"ts\": \"$(stamp)\", \"variant\": \"note\", \"note\": \"$1\"}" >> "$OUT"; }
run() {
  local tag="$1"; shift
  echo "== $tag =="
  local line
  line=$("$@" 2>/dev/null | grep '^{' | tail -1)
  if [ -n "$line" ]; then
    echo "{\"ts\": \"$(stamp)\", \"variant\": \"$tag\", \"result\": $line}" >> "$OUT"
    echo "$line"
  else
    echo "{\"ts\": \"$(stamp)\", \"variant\": \"$tag\", \"error\": true}" >> "$OUT"
  fi
}

QUICK=${1:-}

# ---- 0. the r5 backlog first (never drained: tunnel down r3-r5) ----
if [ "$QUICK" != "quick" ] && [ -f tools_tpu_r5_queue.sh ]; then
  note "r6 queue: draining r5 backlog first"
  bash tools_tpu_r5_queue.sh
fi

note "r6 queue start: spectrum-pass fusion A/Bs (fused_tail on/off, skzap, chirp premul)"

# ---- 1. fused-tail A/B at 2^27 (four_step hosts the epilogue; the
#          monolithic default is the unfused reference plan).  Three
#          legs: legacy 7-pass, fused 5-pass (epilogue + chirp·twiddle
#          premul), fully-fused 4-pass (+ skzap waterfall kernel).
#          Every line now carries plan/hbm_passes/model_hbm_gb from the
#          per-plan count, so roofline_frac is comparable across legs.
run fused_tail_off_27 env SRTB_BENCH_LOG2N=27 SRTB_BENCH_FFT_STRATEGY=four_step \
    SRTB_BENCH_DEADLINE=900 python bench.py --fused-tail off
run fused_tail_on_27  env SRTB_BENCH_LOG2N=27 SRTB_BENCH_FFT_STRATEGY=four_step \
    SRTB_BENCH_DEADLINE=900 python bench.py --fused-tail on
run fused_skzap_27    env SRTB_BENCH_LOG2N=27 SRTB_BENCH_FFT_STRATEGY=four_step \
    SRTB_BENCH_USE_PALLAS=1 SRTB_BENCH_USE_PALLAS_SK=1 \
    SRTB_BENCH_DEADLINE=900 python bench.py --fused-tail on
# monolithic reference on the same sizes (the auto plan below 2^30)
run fused_ref_mono_27 env SRTB_BENCH_LOG2N=27 SRTB_BENCH_DEADLINE=900 \
    python bench.py --fused-tail off
# fused tail on the pallas2 two-pass FFT (epilogue rides the Hermitian
# post after pass 2 — the all-fusions flagship candidate)
run fused_pallas2_27  env SRTB_BENCH_LOG2N=27 SRTB_BENCH_FFT_STRATEGY=pallas2 \
    SRTB_BENCH_USE_PALLAS=1 SRTB_BENCH_USE_PALLAS_SK=1 \
    SRTB_BENCH_DEADLINE=900 python bench.py --fused-tail on

# ---- 2. per-kernel attribution for the fused epilogues (chained-loop
#          rows: fused chirp+RFI hermitian write, fused skzap read) ----
echo "== kernel bench (fused epilogue rows) =="
python -m srtb_tpu.tools.kernel_bench --log2n 28 --reps 5 2>/dev/null \
  | while read -r line; do
      echo "{\"ts\": \"$(stamp)\", \"variant\": \"kernel_r6\", \"result\": $line}" >> "$OUT"
      echo "$line"
    done

if [ "$QUICK" = "quick" ]; then exit 0; fi

# ---- 3. 2^30 staged production segment: fused stage-b epilogue vs
#          legacy.  The staged plan's RFI+chirp sweep was 0.67 s of
#          16 GB traffic at the 819 GB/s roof — the fused leg should
#          recover ~2/7 of the traffic floor.
run staged_fused_off_30 env SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 \
    SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=2700 python bench.py --fused-tail off
run staged_fused_on_30  env SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 \
    SRTB_BENCH_REPS=3 SRTB_BENCH_DEADLINE=2700 python bench.py --fused-tail on
# fully-fused 2^30: staged + pallas legs + skzap waterfall (watfft_len
# 2^14 fits the VMEM row window at 2^15 channels)
run staged_skzap_30     env SRTB_BENCH_LOG2N=30 SRTB_BENCH_LOG2CHAN=15 \
    SRTB_BENCH_REPS=3 SRTB_BENCH_USE_PALLAS=1 SRTB_BENCH_USE_PALLAS_SK=1 \
    SRTB_BENCH_DEADLINE=2700 python bench.py --fused-tail on

note "r6 queue done"
