#!/bin/bash
# DM-grid search over the synthetic J1644-4559 observation: the
# companion to j1644_synthetic.sh for the repo's scale-out addition
# (--dm_list trials sharded over the device mesh, SURVEY.md §2.9).
# A pulse dispersed at DM -478.80 is searched over an 8-trial grid; the
# S/N curve must peak at the injected DM (decoherence kills the
# mismatched trials).  artifacts/j1644_dm_curve.png is exactly this run.
set -eu
DIR=${1:-/tmp/j1644dm}
mkdir -p "$DIR"

python -m srtb_tpu.tools.make_baseband --out "$DIR/bb.bin" \
  --n "2**24" --freq_low "1405+32" --bandwidth " -64" --dm " -478.80" \
  --pulses "2**23" --nbits 2 --pulse_amp 40 --seed 3

python -m srtb_tpu.tools.main \
  --input_file_path "$DIR/bb.bin" \
  --baseband_input_count "2 ** 24" --baseband_input_bits 2 \
  --baseband_format_type simple --baseband_freq_low "1405 + 32" \
  --baseband_bandwidth " -64" --baseband_sample_rate 128e6 \
  --dm_list " -380, -430, -465, -478.80, -495, -530, -580, -650" \
  --spectrum_channel_count "2 ** 11" \
  --baseband_output_file_prefix "$DIR/out_" \
  --signal_detect_signal_noise_threshold 8 --baseband_reserve_sample 0 \
  --mitigate_rfi_spectral_kurtosis_threshold 1.05

python -m srtb_tpu.tools.plot_dm_curve "$DIR/out_dm_trials.jsonl" \
  "$DIR/dm_curve.png"
ls -la "$DIR"/dm_curve.png
