#!/bin/bash
# Flagship-config acceptance run on synthetic data: the J1644-4559
# observation parameters (ref: userspace/srtb_config_1644-4559.cfg —
# 2-bit samples, 128 MSa/s, |DM| = 478.80, inverted 64 MHz band at
# 1405-1469 MHz) over a synthesized baseband with two dispersed pulses,
# end-to-end through the CLI pipeline, then rendered with plot_spectrum.
#
# The reference's acceptance evidence is a real J1644-4559 recording
# (ref: README.md:9-19); no recording ships in either repo, so this is
# the reproducible equivalent: same config, synthetic pulses at known
# positions, detection + waterfall artifact out.  Expected: both
# segments detect (peak at time bin 2048 of 4096, SNR ~60), candidates
# written, PNGs rendered.  artifacts/j1644_synthetic_waterfall.png in
# the repo is segment 0 of exactly this run.
set -eu
DIR=${1:-/tmp/j1644}
mkdir -p "$DIR"

python -m srtb_tpu.tools.make_baseband --out "$DIR/bb.bin" \
  --n "2**25" --freq_low "1405+32" --bandwidth " -64" --dm " -478.80" \
  --pulses "2**23, 3*2**23" --nbits 2 --pulse_amp 40 --seed 3

python -m srtb_tpu.tools.main \
  --input_file_path "$DIR/bb.bin" \
  --baseband_input_count "2 ** 24" --baseband_input_bits 2 \
  --baseband_format_type simple --baseband_freq_low "1405 + 32" \
  --baseband_bandwidth " -64" --baseband_sample_rate 128e6 \
  --dm " -478.80" --spectrum_channel_count "2 ** 11" \
  --baseband_output_file_prefix "$DIR/out_" \
  --signal_detect_signal_noise_threshold 8 --baseband_reserve_sample 0 \
  --mitigate_rfi_spectral_kurtosis_threshold 1.05

# run from the repo root (srtb_tpu importable); glob handles the paths
python -m srtb_tpu.tools.plot_spectrum "$DIR/out_*.0.npy"
ls -la "$DIR"/*.png
